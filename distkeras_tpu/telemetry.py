"""Runtime telemetry: structured metrics + span tracing for the system side.

``observability.py`` covers the *compute* side (FLOPs, MFU, profiler
traces). This module covers the *system* side the reference never had and
the async zoo badly needs: PS RPC latency, commit staleness distributions,
worker window timing, prefetch queue occupancy. A process-local
:class:`MetricsRegistry` holds counters, gauges and bounded histograms; a
``with span("ps.commit"): ...`` tracer records wall-clock durations (and a
bounded event timeline with monotonic timestamps); ``dump_jsonl`` leaves a
machine-readable artifact next to the BENCH_*.json files.

Design constraints (enforced by tests/test_telemetry.py):

- **No jax import.** Nothing here can touch a device, so instrumentation
  can never introduce a device sync on the step path.
- **Lock-free record path.** Counters and histograms shard their state
  per thread (``threading.local``); ``inc``/``record``/``set``/``add``
  touch only the calling thread's shard — no lock, no contention from
  ``host_async`` worker threads. The only locks are on metric *creation*
  (first call for a given name+labels) and shard registration (first call
  per thread per metric); after that the hot path is a dict hit plus a few
  attribute ops (~1 µs).
- **Cleanly disabled.** A default registry is installed at import (the
  telemetry is default-on); ``uninstall()`` turns every module-level
  accessor into a shared no-op metric, so instrumented call sites cost one
  ``None`` check and a no-op method call.

JSONL schema (one object per line; see DESIGN.md §5b):

    {"kind": "counter",   "name": ..., "labels": {...}, "value": N}
    {"kind": "gauge",     "name": ..., "labels": {...}, "value": X}
    {"kind": "histogram", "name": ..., "labels": {...}, "count": N,
     "sum": S, "min": m, "max": M, "p50": ..., "p95": ...,
     "samples_kept": K}
    {"kind": "span", "name": ..., "labels": {...}, "t0": monotonic_start,
     "dur_s": ...}

Histograms are *bounded*: each thread shard keeps a ring of the most
recent ``max_samples`` values (count/sum/min/max stay exact over ALL
samples; percentiles are computed from the kept ring, i.e. they are
recency-weighted once a shard overflows).
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "get_registry", "install", "uninstall", "reset",
    "counter", "gauge", "histogram", "span", "load_jsonl",
    "METRIC_NAMES", "METRIC_PREFIXES", "declared_kind",
    "TraceContext", "current_trace", "use_trace", "inject", "extract",
    "record_trace_span", "flush_at_exit",
    "set_recorder", "get_recorder", "record_event",
    "set_process_index", "process_index", "per_process_path",
]

SCHEMA_VERSION = 1

#: The metric-name registry: every metric the package produces, declared
#: once, name -> instrument kind. Two readers share this dict as the
#: single source of truth: the runtime (``MetricsRegistry._get`` raises on
#: a declared name used with the wrong kind) and the dktlint
#: telemetry-registry checker (``distkeras_tpu/analysis/registry.py``
#: parses this literal from the AST and cross-checks every producer call
#: and consumer reference in the repo). Ad-hoc names outside the declared
#: namespaces (tests, experiments) remain legal — the registry constrains
#: the names it knows about, it does not close the namespace.
#:
#: Keep this a LITERAL dict of string keys/values: the lint suite reads it
#: without importing this module.
METRIC_NAMES = {
    # comms wire accounting (codec + both remote_ps sides)
    "comms.bytes_recv": "counter",
    "comms.bytes_sent": "counter",
    "comms.compress_ratio": "histogram",
    "comms.negotiated": "counter",
    # data plane
    "data.prefetch.producer_errors": "counter",
    "data.prefetch.producer_wait_s": "histogram",
    "data.prefetch.puts": "counter",
    "data.prefetch.queue_depth": "gauge",
    "data.prefetch.queue_depth_samples": "histogram",
    # streaming data service (data/service.py, DESIGN.md §20)
    "data.service.acks": "counter",
    "data.service.client.reconnects": "counter",
    "data.service.client.retries": "counter",
    "data.service.client.rtt_s": "histogram",
    "data.service.client.unavailable": "counter",
    "data.service.cursor": "gauge",
    "data.service.dedup_hits": "counter",
    "data.service.epoch": "gauge",
    "data.service.fetch_rows": "counter",
    "data.service.leased_ranges": "gauge",
    "data.service.leases": "counter",
    "data.service.ranges": "gauge",
    "data.service.releases": "counter",
    "data.service.server.auth_failures": "counter",
    "data.service.server.dispatch": "counter",
    "data.service.stale_acks": "counter",
    # elastic fleet membership (health/membership.py + remote_ps commits)
    "elastic.evictions": "counter",
    # coordinator failover plane (parallel/failover.py, DESIGN.md §17)
    "elastic.failover.epoch": "gauge",
    "elastic.failover.fenced": "counter",
    "elastic.failover.kills": "counter",
    "elastic.failover.promotions": "counter",
    "elastic.failover.repl_dropped": "counter",
    "elastic.failover.repl_errors": "counter",
    "elastic.failover.repl_lag": "gauge",
    "elastic.failover.repl_records": "counter",
    "elastic.failover.resolves": "counter",
    "elastic.late_folds": "counter",
    "elastic.readmissions": "counter",
    "elastic.workers": "gauge",
    # fault injection
    "fault.chaos": "counter",
    "fault.injected": "counter",
    # routed serving fleet (serving/fleet.py, DESIGN.md §22)
    "fleet.affinity.entries": "gauge",
    "fleet.affinity.hit_rate": "gauge",
    "fleet.affinity.hits": "counter",
    "fleet.affinity.misses": "counter",
    "fleet.evictions": "counter",
    "fleet.handoff_failures": "counter",
    "fleet.handoffs": "counter",
    "fleet.replica.queue_depth": "gauge",
    "fleet.replicas": "gauge",
    "fleet.requests": "counter",
    "fleet.requeued": "counter",
    "fleet.sheds": "counter",
    "fleet.version_skew": "gauge",
    # health plane
    "health.alerts.active": "gauge",
    "health.alerts.breaches": "counter",
    "health.alerts.evals": "counter",
    "health.straggler.events": "counter",
    "health.stragglers": "gauge",
    "health.watchdog.idle_s": "gauge",
    "health.watchdog.last_loss": "gauge",
    "health.watchdog.last_update_norm": "gauge",
    "health.watchdog.tripped": "gauge",
    "health.watchdog.trips": "counter",
    "health.worker.clock": "gauge",
    "health.worker.heartbeat_time": "gauge",
    "health.worker.staleness": "gauge",
    "health.worker.straggler": "gauge",
    "health.worker.window_s": "gauge",
    "health.worker.windows": "counter",
    # host-driven async trainer
    "host_async.commit_clock_lag": "histogram",
    "host_async.commit_s": "histogram",
    "host_async.degraded_windows": "counter",
    "host_async.pull_s": "histogram",
    "host_async.save.count": "counter",
    "host_async.save_s": "histogram",
    "host_async.window_s": "histogram",
    # compute-side observability
    "observability.achieved_flops": "gauge",
    "observability.calibration_ratio": "gauge",
    "observability.cost_analysis_unavailable": "counter",
    "observability.flops.while_floor": "counter",
    "observability.flops_per_step": "gauge",
    "observability.mfu": "gauge",
    "observability.mfu_window": "histogram",
    "observability.peak_flops": "gauge",
    # in-process parameter servers
    "ps.commit.count": "counter",
    "ps.commit.handle_s": "histogram",
    "ps.commit.staleness": "histogram",
    "ps.pull.count": "counter",
    # remote (socket) parameter server
    "remote_ps.client.bytes_received": "counter",
    "remote_ps.client.bytes_sent": "counter",
    "remote_ps.client.reconnects": "counter",
    "remote_ps.client.retries": "counter",
    "remote_ps.client.rtt_s": "histogram",
    "remote_ps.client.unavailable": "counter",
    "remote_ps.server.auth_failures": "counter",
    "remote_ps.server.dedup_hits": "counter",
    "remote_ps.server.bytes_received": "counter",
    "remote_ps.server.dispatch": "counter",
    "remote_ps.server.handle_s": "histogram",
    "remote_ps.server.inflight_connections": "gauge",
    # serving plane
    "serving.batch_errors": "counter",
    "serving.batch_size": "histogram",
    "serving.batch_wait_s": "histogram",
    "serving.batches": "counter",
    "serving.compiles": "counter",
    "serving.completed": "counter",
    "serving.deadline_exceeded": "counter",
    "serving.execute_s": "histogram",
    "serving.oldest_request_age_s": "gauge",
    "serving.padding_rows": "histogram",
    "serving.queue_depth": "gauge",
    "serving.rejected": "counter",
    "serving.request_latency_s": "histogram",
    "serving.client.reconnects": "counter",
    "serving.client.retries": "counter",
    "serving.server.auth_failures": "counter",
    "serving.server.inflight_connections": "gauge",
    "serving.server.requests": "counter",
    "serving.shutdown_timeouts": "counter",
    "serving.submitted": "counter",
    # generative serving (KV-cache decode loop, DESIGN.md §14)
    "serving.decode.admitted": "counter",
    "serving.decode.cache_bytes": "gauge",
    "serving.decode.compiles": "counter",
    "serving.decode.deadline_exceeded": "counter",
    "serving.decode.loop_errors": "counter",
    "serving.decode.padded_lanes": "histogram",
    "serving.decode.prefill_s": "histogram",
    "serving.decode.prefills": "counter",
    "serving.decode.queue_depth": "gauge",
    "serving.decode.rejected": "counter",
    "serving.decode.retired": "counter",
    "serving.decode.slot_occupancy": "gauge",
    "serving.decode.slots_active": "gauge",
    "serving.decode.steps": "counter",
    "serving.decode.step_s": "histogram",
    "serving.decode.stream_errors": "counter",
    "serving.decode.tokens": "counter",
    "serving.decode.tokens_per_s": "gauge",
    "serving.decode.ttft_s": "histogram",
    # planet-scale decode layer (DESIGN.md §19): prefix cache, paged KV
    # with host swap, speculative decoding
    "serving.decode.prefix.bytes": "gauge",
    "serving.decode.prefix.evictions": "counter",
    "serving.decode.prefix.exports": "counter",
    "serving.decode.prefix.full_hits": "counter",
    "serving.decode.prefix.hit_rate": "gauge",
    "serving.decode.prefix.hits": "counter",
    "serving.decode.prefix.imports": "counter",
    "serving.decode.prefix.inserts": "counter",
    "serving.decode.prefix.misses": "counter",
    "serving.decode.paged.kv_quant_bytes_saved": "gauge",
    "serving.decode.paged.page_occupancy": "gauge",
    "serving.decode.paged.pages_allocated": "counter",
    "serving.decode.paged.swap_in_failures": "counter",
    "serving.decode.paged.swapped_in": "counter",
    "serving.decode.paged.swapped_out": "counter",
    "serving.decode.spec.accept_rate": "gauge",
    "serving.decode.spec.accepted": "counter",
    "serving.decode.spec.iterations": "counter",
    "serving.decode.spec.proposed": "counter",
    "serving.decode.spec.sampled_accepts": "counter",
    "serving.decode.spec.sampled_resamples": "counter",
    # long-context serving economics (ISSUE 20): chunked prefill
    "serving.decode.chunk.admitted": "counter",
    "serving.decode.chunk.queue_depth": "gauge",
    "serving.decode.chunk.steps": "counter",
    # live rollout / canary / rollback plane (serving/rollout.py,
    # DESIGN.md §18)
    "rollout.canary.agreement": "gauge",
    "rollout.canary.evals": "counter",
    "rollout.canary.mirrored": "counter",
    "rollout.last_swap_time": "gauge",
    "rollout.mirror_errors": "counter",
    "rollout.model_version": "gauge",
    "rollout.promotions": "counter",
    "rollout.publish_dropped": "counter",
    "rollout.publishes": "counter",
    "rollout.rejections": "counter",
    "rollout.rollbacks": "counter",
    "rollout.stale_publishes": "counter",
    "rollout.swap_s": "histogram",
    "rollout.swaps": "counter",
    "rollout.torn_swaps_blocked": "counter",
    "rollout.version_groups": "histogram",
    "rollout.versions_retired": "counter",
    # trainer lifecycle
    "trainer.training_time_s": "gauge",
    # flight recorder (health/recorder.py): bounded forensic ring + dumps
    "recorder.dump_errors": "counter",
    "recorder.dumps": "counter",
    "recorder.events": "counter",
    # artifact loading (load_jsonl crash-tail recovery accounting)
    "telemetry.load.truncated_tail": "counter",
    # time-series metrics plane (health/timeseries.py, DESIGN.md §24):
    # bounded tiered history of the registry + trend detection
    "timeseries.collect_s": "histogram",
    "timeseries.collections": "counter",
    "timeseries.dropped_series": "counter",
    "timeseries.points": "gauge",
    "timeseries.series": "gauge",
    "timeseries.trend_breaches": "counter",
    "timeseries.trends_active": "gauge",
    # chaos soak harness (benchmarks/soak.py): wall-clock-budgeted
    # whole-loop run under a seeded kill schedule
    "soak.cycles": "counter",
    "soak.elapsed_s": "gauge",
    "soak.failed_requests": "counter",
    "soak.kills": "counter",
    "soak.lost_windows": "counter",
    "soak.model_version": "gauge",
    "soak.requests": "counter",
    "soak.version_regressions": "counter",
    "soak.windows": "counter",
    # fleet telemetry collector (health/collector.py; lives on shard 0)
    "collector.batches": "counter",
    "collector.dropped_batches": "counter",
    "collector.dropped_rows": "counter",
    "collector.processes": "gauge",
    "collector.rows": "counter",
    # step-time decomposition (DESIGN.md §15): the canonical phase
    # vocabulary attribution.py renders. Also covered by the
    # "profile.phase." family so per-worker variants stay legal.
    "profile.phase.bookkeep_s": "histogram",
    "profile.phase.collective_s": "histogram",
    "profile.phase.commit_s": "histogram",
    "profile.phase.compute_s": "histogram",
    "profile.phase.data_wait_s": "histogram",
    "profile.phase.decode_s": "histogram",
    "profile.phase.encode_s": "histogram",
    "profile.phase.fold_s": "histogram",
    "profile.phase.h2d_s": "histogram",
    "profile.phase.pull_s": "histogram",
    "profile.phase.window_s": "histogram",
    # op-level attribution (DESIGN.md §21): roofline coverage + per-op
    # time shares, plus the once-per-process degradation counters for
    # backends without a cost model / device profiler. Per-op labeled
    # variants ride the "profile.op." family below.
    "profile.op.capture_unavailable": "counter",
    "profile.op.coverage": "gauge",
    "profile.op.inventory_unavailable": "counter",
    "profile.op.share": "gauge",
    # attention group's share of modeled step time, baseline-vs-kernel
    # (regression_gate --check roofline, ISSUE 18)
    "profile.op.attention_share": "gauge",
    # span names (the `with span("..."):` vocabulary; each also emits a
    # `span.<name>.duration_s` histogram via the prefix family below)
    "serving.compile": "span",
    "serving.decode.compile": "span",
    "serving.decode.warmup": "span",
    "serving.warmup": "span",
    "trainer.compile": "span",
    "trainer.epoch": "span",
    "trainer.finalize": "span",
    "trainer.init": "span",
    "trainer.stage": "span",
    # distributed-trace span vocabulary (DESIGN.md §15). One trace stitches
    # worker window -> transport (retries/reconnects) -> shard folds, or a
    # generate request -> queue wait -> prefill -> decode iterations.
    "trace.commit": "span",
    "trace.compute": "span",
    "trace.decode": "span",
    "trace.fold": "span",
    "trace.prefill": "span",
    "trace.pull": "span",
    "trace.queue_wait": "span",
    "trace.reconnect": "span",
    "trace.request": "span",
    "trace.retry": "span",
    "trace.rpc": "span",
    "trace.server": "span",
    "trace.shard": "span",
    "trace.stream_flush": "span",
    "trace.window": "span",
}

#: Dynamic name families: any name starting with one of these prefixes is
#: declared as a family with the given kind (same literal-dict contract as
#: METRIC_NAMES).
METRIC_PREFIXES = {
    # per-span duration histograms minted by MetricsRegistry.record_span
    "span.": "histogram",
    # device memory stats keyed by whatever the backend reports
    "observability.hbm_": "gauge",
    # distributed-trace span names (DESIGN.md §15)
    "trace.": "span",
    # step-time decomposition phases (benchmarks/attribution.py)
    "profile.phase.": "histogram",
    # op-level roofline shares (profiling/roofline.py), labeled per op
    "profile.op.": "gauge",
}


def declared_kind(name: str):
    """The registered kind for ``name`` ("counter" | "gauge" |
    "histogram" | "span"), or None when the name is undeclared (ad-hoc
    names are allowed; they are simply outside the registry's contract)."""
    k = METRIC_NAMES.get(name)
    if k is not None:
        return k
    for prefix, kind in METRIC_PREFIXES.items():
        if name.startswith(prefix):
            return kind
    return None

# -- distributed trace context (DESIGN.md §15) ------------------------------

#: Header key carrying the trace context on every wire protocol
#: (remote_ps request headers, serving/generation framing). W3C
#: traceparent shape: ``00-<32 hex trace_id>-<16 hex span_id>-01``.
#: Servers ignore unknown header keys, so carrying it is raw-fallback-safe
#: for peers that predate tracing.
TRACEPARENT_KEY = "traceparent"

#: Optional baggage dict riding next to the traceparent (low-cardinality
#: request annotations only: worker id, window number — never values).
TRACE_BAGGAGE_KEY = "tracebaggage"

#: Reserved span-label keys that carry trace identity. ``record_span``
#: strips them before minting the ``span.<name>.duration_s`` histogram
#: (per-trace ids would mint one histogram per span) and the row emitters
#: hoist them to top-level row fields.
_TRACE_KEYS = ("trace_id", "span_id", "parent_id")


class TraceContext:
    """A position in a distributed trace: ``trace_id`` names the whole
    request/window, ``span_id`` names the current span, ``baggage`` carries
    low-cardinality annotations along the entire trace.

    Identity is process-agnostic (ids are random hex minted by
    ``os.urandom``), so a context can be serialized into a wire header with
    :func:`inject`, recovered with :func:`extract`, and adopted on any
    thread with :func:`use_trace` — spans recorded while a context is
    current chain parent -> child automatically."""

    __slots__ = ("trace_id", "span_id", "baggage")

    def __init__(self, trace_id: str, span_id: str,
                 baggage: Optional[Dict[str, str]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.baggage = dict(baggage) if baggage else {}

    @classmethod
    def new_root(cls, **baggage: str) -> "TraceContext":
        return cls(os.urandom(16).hex(), os.urandom(8).hex(), baggage)

    def child(self) -> "TraceContext":
        """A new span position under the same trace (baggage shared)."""
        return TraceContext(self.trace_id, os.urandom(8).hex(), self.baggage)

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, value, baggage: Optional[Dict[str, str]] = None):
        """Parse a traceparent string; None on anything malformed (a
        garbled header must never fail the request it rode in on)."""
        parts = value.split("-") if isinstance(value, str) else []
        if len(parts) != 4 or parts[0] != "00":
            return None
        trace_id, span_id = parts[1], parts[2]
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16)
            int(span_id, 16)
        except ValueError:
            return None
        return cls(trace_id, span_id, baggage)

    def __repr__(self) -> str:
        return f"TraceContext({self.to_traceparent()!r})"


_trace_local = threading.local()


def current_trace() -> Optional[TraceContext]:
    """The calling thread's active trace context, or None (untraced)."""
    return getattr(_trace_local, "ctx", None)


@contextlib.contextmanager
def use_trace(ctx: Optional[TraceContext]):
    """Adopt ``ctx`` as the calling thread's current trace for the block.
    Threads do not inherit context — fan-out sites (shard pools, handler
    threads) adopt the parent explicitly, which is what keeps span
    parentage honest across thread boundaries."""
    prev = getattr(_trace_local, "ctx", None)
    _trace_local.ctx = ctx
    try:
        yield ctx
    finally:
        _trace_local.ctx = prev


def inject(header: Dict[str, Any],
           ctx: Optional[TraceContext] = None) -> Dict[str, Any]:
    """Write ``ctx`` (default: the thread's current trace) into a wire
    header dict in W3C style; no-op when untraced. Returns ``header``."""
    if ctx is None:
        ctx = current_trace()
    if ctx is not None:
        header[TRACEPARENT_KEY] = ctx.to_traceparent()
        if ctx.baggage:
            header[TRACE_BAGGAGE_KEY] = dict(ctx.baggage)
    return header


def extract(header: Dict[str, Any]) -> Optional[TraceContext]:
    """Recover a TraceContext from a wire header; None when absent or
    malformed. The inverse of :func:`inject`."""
    raw = header.get(TRACEPARENT_KEY)
    if not raw:
        return None
    bag = header.get(TRACE_BAGGAGE_KEY)
    return TraceContext.from_traceparent(
        raw, bag if isinstance(bag, dict) else None)


#: Per-thread-shard ring size for histograms. 1024 doubles (per writing
#: thread) bounds memory while keeping p50/p95 meaningful for the window
#: counts real runs produce (a 10-epoch async run commits O(1e3) windows).
DEFAULT_MAX_SAMPLES = 1024

#: Bounded span-event timeline (registry-wide). deque(maxlen=) appends are
#: atomic in CPython, so the span record path needs no lock either.
MAX_SPAN_EVENTS = 4096


def _full_name(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def _span_row(name: str, t0: float, dur_s: float,
              labels: Dict[str, Any]) -> dict:
    """Span event -> row dict. Trace identity keys are hoisted out of the
    labels into top-level fields so consumers (merge views, Chrome export)
    key on ``row["trace_id"]`` while labels stay low-cardinality."""
    row = {"kind": "span", "name": name, "labels": labels,
           "t0": t0, "dur_s": dur_s}
    if labels and "trace_id" in labels:
        row["labels"] = {k: v for k, v in labels.items()
                        if k not in _TRACE_KEYS}
        for k in _TRACE_KEYS:
            if k in labels:
                row[k] = labels[k]
    return row


class _Metric:
    """Shared shard plumbing: per-thread state boxes, created lock-free on
    the hot path after the first call per thread."""

    kind = "metric"

    def __init__(self, name: str, labels: Dict[str, Any]):
        self.name = name
        self.labels = dict(labels)
        self._local = threading.local()
        self._shards: List[Any] = []
        self._shards_lock = threading.Lock()  # shard CREATION only

    def _shard(self):
        shard = getattr(self._local, "shard", None)
        if shard is None:
            shard = self._new_shard()
            self._local.shard = shard
            with self._shards_lock:
                self._shards.append(shard)
        return shard

    def _new_shard(self):
        raise NotImplementedError

    @property
    def full_name(self) -> str:
        return _full_name(self.name, self.labels)

    def row(self) -> dict:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic count. ``inc`` adds to the calling thread's shard; the
    value is the sum over shards (reading concurrent ints is safe under
    the GIL — at worst a read misses an in-flight bump)."""

    kind = "counter"

    def _new_shard(self):
        return [0]

    def inc(self, n: int = 1) -> None:
        if n < 0:
            raise ValueError(f"Counter is monotonic; use a Gauge for "
                             f"up/down values (got inc({n}))")
        self._shard()[0] += n

    @property
    def value(self):
        return sum(s[0] for s in list(self._shards))

    def row(self) -> dict:
        return {"kind": self.kind, "name": self.name, "labels": self.labels,
                "value": self.value}


class Gauge(_Metric):
    """Last-write-wins ``set`` plus lock-free up/down ``add`` deltas:
    ``value = last set + sum of adds`` (in-flight counts use add(±1))."""

    kind = "gauge"

    def __init__(self, name: str, labels: Dict[str, Any]):
        super().__init__(name, labels)
        self._base = 0.0

    def _new_shard(self):
        return [0.0]

    def set(self, value: float) -> None:
        self._base = value

    def add(self, n: float) -> None:
        self._shard()[0] += n

    @property
    def value(self) -> float:
        return self._base + sum(s[0] for s in list(self._shards))

    def row(self) -> dict:
        return {"kind": self.kind, "name": self.name, "labels": self.labels,
                "value": self.value}


class _HistShard:
    __slots__ = ("n", "total", "lo", "hi", "ring", "i", "cap")

    def __init__(self, cap: int):
        self.n = 0
        self.total = 0.0
        self.lo = float("inf")
        self.hi = float("-inf")
        self.ring: List[float] = []
        self.i = 0
        self.cap = cap


class Histogram(_Metric):
    """Bounded histogram: exact count/sum/min/max over every sample, p50/p95
    from a per-thread ring of the most recent ``max_samples`` values."""

    kind = "histogram"

    def __init__(self, name: str, labels: Dict[str, Any],
                 max_samples: int = DEFAULT_MAX_SAMPLES):
        super().__init__(name, labels)
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1, got {max_samples}")
        self.max_samples = int(max_samples)

    def _new_shard(self):
        return _HistShard(self.max_samples)

    def record(self, value: float) -> None:
        v = float(value)
        s = self._shard()
        s.n += 1
        s.total += v
        if v < s.lo:
            s.lo = v
        if v > s.hi:
            s.hi = v
        if len(s.ring) < s.cap:
            s.ring.append(v)
        else:  # overwrite oldest: bounded memory, recency-weighted kept set
            s.ring[s.i] = v
            s.i = (s.i + 1) % s.cap

    def stats(self) -> dict:
        shards = list(self._shards)
        n = sum(s.n for s in shards)
        if n == 0:
            return {"count": 0, "sum": 0.0, "min": None, "max": None,
                    "p50": None, "p95": None, "samples_kept": 0}
        kept = sorted(v for s in shards for v in s.ring)

        def pct(q: float) -> float:
            return kept[min(len(kept) - 1, int(q * len(kept)))]

        return {"count": n,
                "sum": sum(s.total for s in shards),
                "min": min(s.lo for s in shards),
                "max": max(s.hi for s in shards),
                "p50": pct(0.50), "p95": pct(0.95),
                "samples_kept": len(kept)}

    def row(self) -> dict:
        out = {"kind": self.kind, "name": self.name, "labels": self.labels}
        out.update(self.stats())
        return out


class _NullMetric:
    """Shared no-op standing in for every metric when no registry is
    installed — call sites stay branch-free."""

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def add(self, n: float) -> None:
        pass

    def record(self, value: float) -> None:
        pass

    @property
    def value(self):
        return 0


_NULL = _NullMetric()


class MetricsRegistry:
    """Process-local metric store. Creation (``counter``/``gauge``/
    ``histogram``) is get-or-create keyed by (name, labels): the fast path
    is an unlocked dict read (safe in CPython), the miss path takes the
    creation lock once per metric."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, tuple], _Metric] = {}
        self._create_lock = threading.Lock()
        self.spans: "collections.deque" = collections.deque(
            maxlen=MAX_SPAN_EVENTS)

    def _get(self, cls, name: str, labels: Dict[str, Any], **kw) -> _Metric:
        key = (name, tuple(sorted(labels.items())))
        m = self._metrics.get(key)
        if m is None:
            # the registry contract (METRIC_NAMES) is enforced on the
            # creation path only — the hot path stays a bare dict hit
            want = declared_kind(name)
            if want is not None and want != cls.kind:
                raise TypeError(
                    f"metric {name!r} is declared as a {want} in "
                    f"telemetry.METRIC_NAMES but requested as {cls.kind}")
            with self._create_lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, labels, **kw)
                    self._metrics[key] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {_full_name(name, labels)!r} already "
                            f"registered as {m.kind}, requested {cls.kind}")
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, max_samples: int = DEFAULT_MAX_SAMPLES,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, max_samples=max_samples)

    def record_span(self, name: str, t0: float, dur_s: float,
                    labels: Dict[str, Any]) -> None:
        self.spans.append((name, t0, dur_s, labels))
        rec = _recorder
        if rec is not None:  # flight-recorder ring (lock-light, bounded)
            rec.record_span_event(name, t0, dur_s, labels)
        hist_labels = labels
        if labels and "trace_id" in labels:
            # trace ids are per-span unique: keeping them would mint one
            # histogram per event. Identity stays on the timeline only.
            hist_labels = {k: v for k, v in labels.items()
                           if k not in _TRACE_KEYS}
        self.histogram(f"span.{name}.duration_s", **hist_labels).record(dur_s)

    # -- export -----------------------------------------------------------
    def rows(self) -> Iterator[dict]:
        for m in list(self._metrics.values()):
            yield m.row()
        for name, t0, dur, labels in list(self.spans):
            yield _span_row(name, t0, dur, labels)

    def recent_spans(self, limit: int = 100) -> List[dict]:
        """The newest ``limit`` span events as row dicts (oldest first) —
        the live ``recent-spans`` introspection endpoint's payload."""
        events = list(self.spans)[-max(0, int(limit)):]
        return [_span_row(name, t0, dur, labels)
                for name, t0, dur, labels in events]

    def snapshot(self) -> dict:
        """Structured view for ``Trainer.get_telemetry()`` and the live
        ``metrics-snapshot`` endpoint: metric rows grouped by kind, keyed by
        ``name{label=...}``.

        Lock-consistent: the metric SET and the span timeline are copied
        under the creation lock, so a snapshot taken from an introspection
        handler thread never sees a half-registered metric or tears the
        span deque against a concurrent ``clear()``. Individual values are
        still read without stopping writers (a read may miss an in-flight
        bump — monotonic, never garbage)."""
        with self._create_lock:
            metrics = list(self._metrics.values())
            spans = list(self.spans)
        out: dict = {"counters": {}, "gauges": {}, "histograms": {},
                     "spans": []}
        rows = [m.row() for m in metrics] + [
            _span_row(name, t0, dur, labels)
            for name, t0, dur, labels in spans]
        for row in rows:
            kind = row["kind"]
            if kind == "span":
                out["spans"].append(row)
                continue
            key = _full_name(row["name"], row["labels"])
            if kind == "counter":
                out["counters"][key] = row["value"]
            elif kind == "gauge":
                out["gauges"][key] = row["value"]
            else:
                out["histograms"][key] = {
                    k: v for k, v in row.items()
                    if k not in ("kind", "name", "labels")}
        return out

    def dump_jsonl(self, path: str) -> str:
        """Write every metric + span event as JSON lines; returns ``path``."""
        with open(path, "w") as f:
            f.write(json.dumps({"kind": "meta", "schema": SCHEMA_VERSION,
                                "unix_time": time.time()}) + "\n")
            for row in self.rows():
                f.write(json.dumps(row) + "\n")
        return path

    def clear(self) -> None:
        with self._create_lock:
            self._metrics.clear()
        self.spans.clear()


def load_jsonl(path: str) -> List[dict]:
    """Load a dumped artifact back into a list of row dicts (meta line
    included as row 0).

    A truncated TRAILING line — the shape a crash-time dump leaves when the
    process dies mid-write — is tolerated: the parsed prefix is returned
    and a warning is emitted. Corruption anywhere *before* the last line
    still raises (that artifact is damaged, not merely cut short)."""
    with open(path) as f:
        lines = [ln for ln in (raw.strip() for raw in f) if ln]
    rows = []
    for i, line in enumerate(lines):
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                import warnings

                # silent corruption becomes visible in fleet digests: the
                # recovery is tolerated but COUNTED, not just warned about
                counter("telemetry.load.truncated_tail").inc()
                warnings.warn(
                    f"{path}: dropping truncated trailing line "
                    f"({line[:60]!r}...); returning the "
                    f"{len(rows)}-row parsed prefix (crash-time dump)",
                    RuntimeWarning, stacklevel=2)
                break
            raise
    return rows


# -- module-level default registry (telemetry is default-ON) ----------------

_default = MetricsRegistry()
_installed: Optional[MetricsRegistry] = _default


def get_registry() -> Optional[MetricsRegistry]:
    return _installed


def install(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process registry (tests install a fresh one per case)."""
    global _installed
    _installed = registry
    return registry


def uninstall() -> None:
    """Disable telemetry: module-level accessors become no-ops."""
    global _installed
    _installed = None


def reset() -> MetricsRegistry:
    """Install a fresh registry (and return it) — run isolation helper."""
    return install(MetricsRegistry())


def counter(name: str, **labels):
    reg = _installed
    return _NULL if reg is None else reg.counter(name, **labels)


def gauge(name: str, **labels):
    reg = _installed
    return _NULL if reg is None else reg.gauge(name, **labels)


def histogram(name: str, **labels):
    reg = _installed
    return _NULL if reg is None else reg.histogram(name, **labels)


@contextlib.contextmanager
def span(name: str, **labels):
    """Time a block into ``span.<name>.duration_s`` (+ the event timeline).
    Timestamps are ``time.monotonic``-class (perf_counter); pairs of events
    order correctly within a process but mean nothing across processes.

    When the calling thread has an active :class:`TraceContext` (via
    :func:`use_trace` or an enclosing ``span``), the event is recorded as a
    child of that context, a fresh child context is made current for the
    duration of the block, and that context is yielded (None when
    untraced) — so nested spans chain parent -> child and the context can
    be injected into outbound wire headers."""
    reg = _installed
    if reg is None:
        yield None
        return
    parent = current_trace()
    if parent is None:
        t0 = time.perf_counter()
        try:
            yield None
        finally:
            reg.record_span(name, t0, time.perf_counter() - t0, labels)
        return
    ctx = parent.child()
    labels = dict(labels, trace_id=ctx.trace_id, span_id=ctx.span_id,
                  parent_id=parent.span_id)
    _trace_local.ctx = ctx
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        _trace_local.ctx = parent
        reg.record_span(name, t0, time.perf_counter() - t0, labels)


def record_trace_span(ctx: Optional["TraceContext"], name: str, t0: float,
                      dur_s: float, **labels) -> None:
    """Record one already-measured span as a child of ``ctx`` (plain
    untraced event when ctx is None). For code whose span boundaries do
    not nest as a ``with`` block — e.g. the generation scheduler, where a
    request's queue-wait starts on the submitting thread and ends
    iterations later on the scheduler thread. ``t0`` must be a
    ``time.perf_counter`` reading (the registry's span time base)."""
    reg = _installed
    if reg is None:
        return
    if ctx is not None:
        child = ctx.child()
        labels = dict(labels, trace_id=child.trace_id,
                      span_id=child.span_id, parent_id=ctx.span_id)
    reg.record_span(name, t0, dur_s, labels)


# -- flight-recorder sink (health/recorder.py plugs in here) -----------------
#
# The recorder is a plain object with ``record(kind, **fields)`` and
# ``record_span_event(name, t0, dur_s, labels)`` methods; telemetry holds
# only the slot so the dependency points health -> telemetry, never back.
# The slot is module-global and read without a lock (same CPython-read
# discipline as ``_installed``): the record paths stay lock-free.

_recorder: Optional[Any] = None


def set_recorder(rec: Optional[Any]) -> Optional[Any]:
    """Install (or clear, with None) the process flight-recorder sink."""
    global _recorder
    _recorder = rec
    return rec


def get_recorder() -> Optional[Any]:
    return _recorder


def record_event(kind: str, /, **fields) -> None:
    """Append one structured event to the flight-recorder ring (no-op when
    no recorder is installed). Events are forensic breadcrumbs — wire
    outcomes, membership transitions, window phase profiles, alerts — that
    only leave the process inside a postmortem bundle."""
    rec = _recorder
    if rec is not None:
        rec.record(kind, **fields)


# -- per-process artifact identity -------------------------------------------
#
# telemetry/health must stay device-runtime-free, so the process index is
# PUSHED in by the trainers (which know the real one) instead of read from
# the accelerator runtime here. Default 0 = single-process runs unchanged.

_process_index = 0


def set_process_index(index: int) -> int:
    """Declare this process's fleet index (trainers call this once the
    runtime is up); stamps ``flush_at_exit`` artifacts and recorder dump
    paths so shared-FS fleets cannot clobber each other."""
    global _process_index
    index = int(index)
    if index < 0:
        raise ValueError(f"process index must be >= 0, got {index}")
    _process_index = index
    return _process_index


def process_index() -> int:
    return _process_index


def per_process_path(path: str) -> str:
    """``path`` suffixed with this process's identity (``.p{index}``).
    Merge tooling globs the family (``path.p*``)."""
    return f"{path}.p{_process_index}"


# -- crash-safe artifact flush ----------------------------------------------

_flush_state: Dict[str, Optional[str]] = {"path": None}


def flush_at_exit(path: str) -> str:
    """Arrange for the installed registry to be dumped to
    ``path.p{process_index}`` at interpreter exit, so the span/metric
    artifact survives a crashed or watchdog-killed run
    (``checkpoint_and_raise`` unwinds through here) and multi-process
    fleets on a shared FS each keep their own copy. Idempotent: one atexit
    hook total, the most recent path wins; the suffix is applied at FLUSH
    time so a process index declared after this call still lands. The hook
    is a no-op when telemetry is uninstalled at exit time."""
    first = _flush_state["path"] is None
    _flush_state["path"] = str(path)
    if first:
        atexit.register(_flush_now)
    return per_process_path(_flush_state["path"])


def _flush_now() -> Optional[str]:
    path, reg = _flush_state["path"], _installed
    if path is None or reg is None:
        return None
    try:
        return reg.dump_jsonl(per_process_path(path))
    except OSError:
        return None  # a dead disk at exit must not mask the real failure
