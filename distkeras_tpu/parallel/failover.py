"""Coordinator failover: write-behind authority replication + lease handoff.

DESIGN.md §6 was honest that a shard-0 crash ends the run: the elastic
fleet (DESIGN.md §13) made follower shards disposable, but the
coordinator's AUTHORITY — its clock, the membership/lease table, the
commit-dedup window, the history barrier state, the fleet telemetry
collector — lived in exactly one process. This module replicates that
authority to a designated **standby** service so a coordinator death is
a lease handoff, not a checkpoint-restart (DESIGN.md §17):

- :class:`Replicator` runs ON the coordinator: every folded commit is
  forwarded to the standby as a ``repl_append`` record over the existing
  wire framing — carrying the RAW received blobs (zero re-encode) plus
  the fold's ``(at_fold, applied_weight)`` verdict — and a ``coord_lease``
  heartbeat at lease/3 cadence streams the clock + membership export.
  The log is write-BEHIND: the commit is acked to the worker first, the
  record ships asynchronously (a bounded queue + one background thread),
  so replication adds zero latency to the fold path.

- :class:`StandbyState` runs on the standby service: each commit record
  replays through :meth:`ParameterServer.replay` — the SAME jitted fold
  at the SAME clock with the SAME float32 weight — so the replica center
  is bit-identical to the coordinator's after every applied record.
  Membership, histories, telemetry batches, and the dedup window mirror
  as plain state.

- **Promotion** is lazy and lease-driven, the same idiom as
  ``Membership.sweep``: there is no failure-detector thread — the first
  ``coordinator`` query after the coordinator's lease lapses finds the
  lapse and promotes right there (workers issue that query from their
  reconnect path). Promotion is fenced by an **epoch number**: it bumps
  the epoch, a second promotion is rejected, and a deposed coordinator
  that comes back hears ``{"fenced": true, epoch}`` on its next
  heartbeat and stops serving coordinator ops (replying with a redirect
  instead) — split-brain cannot fold two divergent centers.

Loss window (documented, DESIGN.md §17): a commit the coordinator acked
but whose record had not yet shipped when it died is absent from the
replica — the standby's clock pins forward over the gap (``replay``
returns it; ``gaps`` counts it honestly) and follower shards are one
fold ahead of the replica for those records. Tests and the failover
probe close the window deterministically with :meth:`Replicator.flush`.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Callable, Optional

from distkeras_tpu import telemetry
from distkeras_tpu.health.collector import TelemetryCollector
from distkeras_tpu.health.heartbeat import StragglerDetector
from distkeras_tpu.health.membership import DEFAULT_LEASE_S, Membership
from distkeras_tpu.parallel import remote_ps

#: Default coordinator lease: the standby grants the coordinator this
#: long between heartbeats (sent at lease/3) before the next coordinator
#: query may promote. Shorter than the worker lease — a dead coordinator
#: must be replaced before worker leases start lapsing en masse.
DEFAULT_COORD_LEASE_S = 10.0


class Replicator:
    """The coordinator's write-behind log shipper (one per coordinator).

    Thread-safe producers (:meth:`record_commit` / :meth:`record_history`
    / :meth:`record_telemetry` are called from the service's handler
    threads) enqueue onto a bounded queue; one daemon thread drains it
    over a persistent socket to the standby, acking record-by-record.
    A full queue DROPS the record with a counter — replication must
    never backpressure the fold path (the standby's ``replay`` closes
    the resulting clock gap honestly).
    """

    #: queue bound: at ~1 record per commit this is minutes of slack at
    #: test rates and seconds at production rates — enough to ride out a
    #: standby GC pause, small enough that a dead standby cannot grow
    #: coordinator RAM.
    QUEUE_MAX = 512

    def __init__(self, standby_address: str, token: Optional[str] = None,
                 *, lease_s: float = DEFAULT_COORD_LEASE_S,
                 members_fn: Optional[Callable[[], dict]] = None,
                 clock_fn: Optional[Callable[[], int]] = None,
                 on_fenced: Optional[Callable[[int], None]] = None,
                 time_fn: Callable[[], float] = time.time,
                 timeout: float = 5.0):
        host, port = standby_address.rsplit(":", 1)
        self.standby_address = standby_address
        self._addr = (host, int(port))
        self.token = token
        self.lease_s = float(lease_s)
        self._members_fn = members_fn
        self._clock_fn = clock_fn
        self._on_fenced = on_fenced
        self._time = time_fn
        self._timeout = float(timeout)
        self.epoch = 0
        self.fenced = False
        self.fenced_epoch = 0
        self._q: queue.Queue = queue.Queue(maxsize=self.QUEUE_MAX)
        self._rseq = 0
        self._rseq_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        try:
            self._q.put_nowait(None)  # wake the drain loop immediately
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._drop_sock()

    # -- producers (service handler threads) ------------------------------
    def _next_rseq(self) -> int:
        with self._rseq_lock:
            self._rseq += 1
            return self._rseq

    def record_commit(self, *, blobs, codec: str, at_fold: int,
                      weight: float, last_update: int,
                      cid: Optional[str], seq) -> None:
        """Ship one folded commit: the raw wire blobs as received, plus
        the coordinator's fold verdict — everything the standby needs to
        replay the identical fold and to answer a dedup'd retry."""
        header = {"op": "repl_append", "kind": "commit",
                  "rseq": self._next_rseq(), "codec": codec,
                  "at_fold": int(at_fold), "weight": float(weight),
                  "last_update": int(last_update)}
        if cid is not None and seq is not None:
            header["cid"], header["seq"] = cid, int(seq)
        self._enqueue(header, [bytes(b) for b in blobs])

    def record_history(self, pid: int, windows: list) -> None:
        self._enqueue({"op": "repl_append", "kind": "history",
                       "rseq": self._next_rseq(), "pid": int(pid),
                       "windows": windows})

    def record_telemetry(self, pid: int, rows: list) -> None:
        self._enqueue({"op": "repl_append", "kind": "telemetry",
                       "rseq": self._next_rseq(), "pid": int(pid),
                       "rows": list(rows)})

    def _enqueue(self, header: dict, blobs=()) -> None:
        if self._stop.is_set() or self.fenced:
            return  # a deposed coordinator stops streaming (DESIGN.md §17)
        try:
            self._q.put_nowait((header, list(blobs)))
        except queue.Full:
            telemetry.counter("elastic.failover.repl_dropped").inc()

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every record enqueued BEFORE this call is acked by
        the standby (plus one fresh heartbeat) — how tests and the
        failover probe close the write-behind loss window on demand."""
        done = threading.Event()
        try:
            self._q.put(("flush", done), timeout=timeout)
        except queue.Full:
            return False
        return done.wait(timeout)

    def heartbeat(self) -> None:
        """One synchronous ``coord_lease`` renewal, for deterministic
        tests (the drain loop sends these on its own at lease/3)."""
        self._heartbeat_once()

    # -- drain loop -------------------------------------------------------
    def _loop(self) -> None:
        interval = max(0.05, self.lease_s / 3.0)
        next_hb = time.monotonic()  # first tick heartbeats immediately
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_hb:
                self._heartbeat_once()
                next_hb = now + interval
            try:
                item = self._q.get(timeout=max(0.01, next_hb -
                                               time.monotonic()))
            except queue.Empty:
                continue
            if item is None:
                break
            if item[0] == "flush":
                self._heartbeat_once()
                item[1].set()
                continue
            self._send_record(*item)

    def _send_record(self, header: dict, blobs) -> None:
        telemetry.gauge("elastic.failover.repl_lag").set(self._q.qsize())
        try:
            resp = self._rt(header, blobs)
        except (ConnectionError, socket.timeout, OSError, RuntimeError):
            # the record is LOST (the documented write-behind window);
            # the standby's replay pins its clock over the gap
            telemetry.counter("elastic.failover.repl_errors").inc()
            return
        if resp.get("fenced"):
            self._handle_fenced(resp)
        else:
            telemetry.counter("elastic.failover.repl_records").inc()

    def _heartbeat_once(self) -> None:
        header = {"op": "coord_lease", "epoch": self.epoch}
        if self._clock_fn is not None:
            header["clock"] = int(self._clock_fn())
        if self._members_fn is not None:
            header["members"] = self._members_fn()
        try:
            resp = self._rt(header)
        except (ConnectionError, socket.timeout, OSError, RuntimeError):
            telemetry.counter("elastic.failover.repl_errors").inc()
            return
        if resp.get("fenced"):
            self._handle_fenced(resp)

    def _handle_fenced(self, resp: dict) -> None:
        if self.fenced:
            return
        self.fenced = True
        self.fenced_epoch = int(resp.get("epoch", 0))
        telemetry.counter("elastic.failover.fenced").inc()
        telemetry.record_event("failover", transition="fenced",
                               epoch=self.fenced_epoch)
        if self._on_fenced is not None:
            try:
                self._on_fenced(self.fenced_epoch)
            except Exception:
                pass  # fencing must not kill the drain thread

    # -- transport (single persistent socket, one reconnect) --------------
    def _rt(self, header: dict, blobs=()) -> dict:
        header = dict(header)
        if self.token is not None:
            header["token"] = self.token
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        self._addr, timeout=self._timeout)
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                self._sock.settimeout(self._timeout)
                remote_ps.send_message(self._sock, header, blobs)
                resp, _ = remote_ps.recv_message(self._sock)
                break
            except (ConnectionError, socket.timeout, OSError):
                self._drop_sock()
                if attempt:
                    raise
        if "error" in resp:
            raise RuntimeError(f"standby refused: {resp['error']}")
        return resp

    def _drop_sock(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class StandbyState:
    """The standby's mirror of the coordinator's authority + the
    promotion state machine. Attached to a DARK
    :class:`~distkeras_tpu.parallel.remote_ps.ParameterServerService`
    (``svc.standby = this``, ``svc.is_standby = True``): until promotion
    the service answers only replication/health/discovery ops.
    """

    #: bounded mirrors: the dedup window matches the service's own cache
    #: scale; telemetry keeps the freshest batches only (the collector it
    #: seeds is itself bounded).
    DEDUP_MIRROR = 512
    TELEMETRY_MIRROR = 64

    def __init__(self, service, *, lease_s: float = DEFAULT_COORD_LEASE_S,
                 member_lease_s: float = DEFAULT_LEASE_S,
                 straggler: Optional[StragglerDetector] = None,
                 time_fn: Callable[[], float] = time.time):
        self.service = service
        self.lease_s = float(lease_s)
        self.member_lease_s = float(member_lease_s)
        self.straggler = straggler
        self._time = time_fn
        self._lock = threading.Lock()
        self.promoted = False
        self.epoch = 0  # highest epoch heard from the live coordinator
        self.last_renewal = time_fn()  # lease granted at construction
        self.applied = 0  # rseq high-water mark
        self.gaps = 0  # commits lost in the write-behind window
        self._coord_clock = 0
        self._members: dict = {}
        self._histories: dict = {}
        self._dedup: list = []  # (cid, seq, reply) mirror
        self._telemetry: list = []  # (pid, rows) batches
        self._codecs: dict = {}  # wire name -> per-stream _TreeCodec

    # -- replication stream (service handler threads) ----------------------
    def handle_append(self, header: dict, blobs: list) -> dict:
        with self._lock:
            if self.promoted:
                # the sender is a deposed coordinator still streaming
                return {"fenced": True, "epoch": self.epoch}
            self.last_renewal = self._time()  # a record is proof of life
            rseq = int(header.get("rseq", 0))
            if rseq and rseq <= self.applied:
                return {"ok": True, "applied": self.applied, "dup": True}
            kind = header.get("kind", "commit")
            if kind == "commit":
                self._apply_commit_locked(header, blobs)
            elif kind == "history":
                self._histories[int(header["pid"])] = header["windows"]
            elif kind == "telemetry":
                self._telemetry.append((int(header.get("pid", -1)),
                                        list(header.get("rows", []))))
                del self._telemetry[:-self.TELEMETRY_MIRROR]
            if rseq:
                self.applied = rseq
            return {"ok": True, "applied": self.applied}

    def _apply_commit_locked(self, header: dict, blobs: list) -> None:
        codec = self._codecs.get(header.get("codec", "raw"))
        if codec is None:
            codec = self.service.codec.with_wire(header.get("codec", "raw"))
            self._codecs[header.get("codec", "raw")] = codec
        delta = codec.decode(blobs, kind="commit")
        gap = self.service.ps.replay(delta, header["at_fold"],
                                     header["weight"],
                                     header.get("last_update", 0))
        if gap > 0:
            self.gaps += gap
        cid, seq = header.get("cid"), header.get("seq")
        if cid is not None and seq is not None:
            # mirror the coordinator's dedup verdict: a worker that
            # retries an acked-but-lost-reply commit AFTER promotion gets
            # the original answer instead of a double fold
            self._dedup.append((cid, int(seq),
                                {"at_fold": int(header["at_fold"]),
                                 "weight": float(header["weight"])}))
            del self._dedup[:-self.DEDUP_MIRROR]

    def handle_lease(self, header: dict) -> dict:
        with self._lock:
            if self.promoted:
                return {"fenced": True, "epoch": self.epoch}
            self.last_renewal = self._time()
            self.epoch = max(self.epoch, int(header.get("epoch", 0)))
            if header.get("clock") is not None:
                self._coord_clock = int(header["clock"])
            if header.get("members") is not None:
                self._members = dict(header["members"])
            return {"ok": True, "lease_s": self.lease_s,
                    "epoch": self.epoch}

    # -- discovery + promotion ---------------------------------------------
    def lease_remaining(self) -> float:
        with self._lock:
            return (self.last_renewal + self.lease_s) - self._time()

    def coordinator_view(self) -> dict:
        """Answer "who is the coordinator?" — and notice a lapsed lease
        while answering: promotion is lazy, exactly like membership's
        sweep; the first query after the lapse performs the handoff."""
        self.maybe_promote()
        svc = self.service
        with self._lock:
            if self.promoted:
                address = svc.advertised
            else:
                address = (svc.shard_addresses[0]
                           if svc.shard_addresses else None)
            return {"address": address, "epoch": self.epoch,
                    "promoted": self.promoted, "standby": svc.advertised,
                    "applied": self.applied, "gaps": self.gaps,
                    "lease_remaining_s": round(
                        self.last_renewal + self.lease_s - self._time(), 3)}

    def maybe_promote(self, force: bool = False) -> tuple:
        """Promote when the coordinator's lease has lapsed (or ``force``,
        for deterministic handoffs in tests/drills). Returns
        ``(promoted_now, reason)``; a second promotion is always
        rejected — the epoch fence admits exactly one handoff."""
        with self._lock:
            if self.promoted:
                return False, "already promoted (epoch "\
                    f"{self.epoch}): double promotion rejected"
            if not force and self._time() <= self.last_renewal + self.lease_s:
                return False, "coordinator lease still live"
            self._promote_locked("forced" if force else "lease lapsed")
            return True, "promoted"

    def _promote_locked(self, reason: str) -> None:
        svc = self.service
        self.epoch += 1
        self.promoted = True
        # authority restore, in dependency order: membership first (the
        # commit handler consults it), then the mirrors the handler and
        # the health plane read
        m = Membership(lease_s=self.member_lease_s,
                       straggler=self.straggler, time_fn=self._time)
        m.restore(self._members)
        svc.membership = m
        # the TelemetryCollector + SLO/health plane re-mount HERE: a
        # fresh collector seeded from the replicated batches, served by
        # the same telemetry_put/telemetry_merged/status ops
        col = TelemetryCollector()
        col.adopt_batches(self._telemetry)
        svc.collector = col
        with svc._hist_cv:
            for pid, windows in self._histories.items():
                svc._histories.setdefault(pid, windows)
            svc._hist_cv.notify_all()
        for cid, seq, reply in self._dedup:
            svc._dedup_put(cid, seq, reply)
        svc.is_standby = False  # the dark gate lifts: data ops now serve
        svc.coord_epoch = self.epoch
        if svc.shard_addresses:
            addresses = list(svc.shard_addresses)
            addresses[0] = svc.advertised
            svc.shard_addresses = addresses
        telemetry.counter("elastic.failover.promotions").inc()
        telemetry.gauge("elastic.failover.epoch").set(self.epoch)
        telemetry.record_event("failover", transition="promote",
                               epoch=self.epoch, reason=reason,
                               clock=int(svc.ps.num_updates),
                               gaps=self.gaps)

    def handle_promote(self, force: bool = False) -> dict:
        did, reason = self.maybe_promote(force=force)
        with self._lock:
            return {"promoted": did, "epoch": self.epoch, "reason": reason,
                    "address": self.service.advertised}
