"""Sequence/context parallelism: the long-context training substrate.

Splits the SEQUENCE dimension of a causal LM over a ``seq`` mesh axis
(ring attention moves k/v blocks around the ring; ops/ring_attention.py) and
the batch over ``workers`` — composable data x context parallelism. Gradients
are psum'd over both axes; the loss is the exact global-mean token loss, so
an (w x s) step equals the single-device step on the same global batch.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import engine
from distkeras_tpu.parallel import mesh as mesh_lib
from distkeras_tpu.utils.jax_compat import shard_map

SEQ_AXIS = "seq"


def make_sp_mesh(num_workers: int = 1, seq_parallelism: int = 1,
                 devices=None) -> Mesh:
    """(workers, seq) mesh: batch parallelism outer, sequence inner (adjacent
    devices share the ring, so k/v hops ride the shortest ICI links)."""
    devices = list(devices if devices is not None else jax.devices())
    need = num_workers * seq_parallelism
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(num_workers, seq_parallelism)
    return Mesh(grid, (mesh_lib.WORKER_AXIS, SEQ_AXIS))


def shift_labels(input_ids: np.ndarray) -> np.ndarray:
    """Host-side next-token labels: labels[t] = ids[t+1]; final position
    ignored (-1). Done globally BEFORE sequence sharding so block boundaries
    need no device-to-device shift."""
    labels = np.full_like(np.asarray(input_ids), -1)
    labels[:, :-1] = input_ids[:, 1:]
    return labels


def build_sp_train_step(model, tx: optax.GradientTransformation, mesh: Mesh,
                        donate: bool = True):
    """Compiled sequence-parallel LM train step.

    Returns ``(step_fn, place_state, place_batch)``:
    - ``step_fn(state, batch) -> (state, metrics)`` where batch is
      ``{"input_ids": [B, T], "labels": [B, T]}`` int32 arrays; B sharded
      over ``workers``, T over ``seq``; labels < 0 are ignored.
    - metrics: global mean ``loss`` and token ``accuracy``.

    The model must be built with ``attention="ring", axis_name="seq"``.
    """

    def local_step(params, opt_state, step_i, input_ids, labels):
        def loss_sum(p):
            logits = model.apply({"params": p}, input_ids, train=True)
            valid = labels >= 0
            safe = jnp.where(valid, labels, 0).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
            nll = -jnp.sum(jnp.where(valid, ll, 0.0))
            hits = jnp.sum(jnp.where(
                valid, (jnp.argmax(logits, -1) == safe), False))
            count = jnp.sum(valid)
            return nll, (hits, count)

        (nll, (hits, count)), grads = jax.value_and_grad(
            loss_sum, has_aux=True)(params)
        axes = (mesh_lib.WORKER_AXIS, SEQ_AXIS)
        total_nll = jax.lax.psum(nll, axes)
        total_hits = jax.lax.psum(hits.astype(jnp.float32), axes)
        total_count = jnp.maximum(
            jax.lax.psum(count.astype(jnp.float32), axes), 1.0)
        grads = jax.lax.psum(grads, axes)
        grads = jax.tree.map(lambda g: g / total_count, grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        ms = {"loss": total_nll / total_count,
              "accuracy": total_hits / total_count}
        return params, opt_state, step_i + 1, ms

    data_spec = P(mesh_lib.WORKER_AXIS, SEQ_AXIS)
    shmapped = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(), P(), data_spec, data_spec),
        out_specs=(P(), P(), P(), P()),
        check_vma=False)

    def step_fn(state: engine.TrainState, batch) -> Tuple[engine.TrainState, dict]:
        params, opt_state, step_i, ms = jitted(
            state.params, state.opt_state, state.step,
            batch["input_ids"], batch["labels"])
        return engine.TrainState(step=step_i, params=params,
                                 opt_state=opt_state), ms

    jitted = jax.jit(shmapped, donate_argnums=(0, 1) if donate else ())

    def place_state(state):
        return mesh_lib.put_global(state, NamedSharding(mesh, P()))

    def place_batch(batch):
        return mesh_lib.put_global(batch, NamedSharding(mesh, data_spec))

    return step_fn, place_state, place_batch


def init_sp_state(model, tx, mesh, batch_shape: Tuple[int, int],
                  seed: int = 0) -> engine.TrainState:
    """Init params OUTSIDE shard_map with full-attention semantics (weights
    are shared between attention impls), replicated on the mesh."""
    b, t_local = batch_shape
    # a full-attention twin with identical params for shape-only init
    twin = model.clone(attention="full")
    params = twin.init(jax.random.key(seed),
                       jnp.zeros((b, t_local), jnp.int32),
                       train=False)["params"]
    state = engine.TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=tx.init(params))
    return mesh_lib.put_global(state, NamedSharding(mesh, P()))
