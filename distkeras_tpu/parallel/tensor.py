"""Tensor parallelism: param partition rules + GSPMD train step.

Not a reference-parity obligation (dist-keras has no TP — SURVEY.md §2), but
a first-class capability of this framework: BASELINE config 5 names
"pjit-sharded data-parallel" for ViT-L, and large transformer models need
their matmuls split over the ``model`` mesh axis.

Design (the scaling-book recipe): pick a mesh (workers × model), annotate
param shardings by PATH RULES (regex -> PartitionSpec), shard the batch over
``workers``, jit, and let GSPMD insert the collectives (all-reduce of grads
over workers, all-gather/reduce-scatter around the model-sharded matmuls).
No hand-written collectives on this path at all.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import engine
from distkeras_tpu import precision as precision_lib
from distkeras_tpu.parallel import collectives
from distkeras_tpu.parallel import mesh as mesh_lib
from distkeras_tpu.utils.jax_compat import shard_map

Rules = Sequence[Tuple[str, P]]

# Default rules for the in-tree model zoo (transformer + conv families).
# First match wins; unmatched params replicate. Megatron-style pairing:
# column-parallel into the nonlinearity, row-parallel out of it.
DEFAULT_RULES: Rules = (
    (r"attn/qkv/kernel$", P(None, mesh_lib.MODEL_AXIS)),
    (r"attn/out/kernel$", P(mesh_lib.MODEL_AXIS, None)),
    (r"mlp/fc1/kernel$", P(None, mesh_lib.MODEL_AXIS)),
    (r"mlp/fc2/kernel$", P(mesh_lib.MODEL_AXIS, None)),
    (r"tok_embed/embedding$", P(mesh_lib.MODEL_AXIS, None)),  # vocab-sharded
    (r"mlm_head/kernel$", P(None, mesh_lib.MODEL_AXIS)),
    (r"head/kernel$", P(None, mesh_lib.MODEL_AXIS)),
    (r"dense.*/kernel$", P(None, mesh_lib.MODEL_AXIS)),
)


def path_str(path) -> str:
    """jax tree path -> 'a/b/c' string for rule matching."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def partition_specs(params: Any, rules: Optional[Rules] = None,
                    mesh: Optional[Mesh] = None) -> Any:
    """PartitionSpec pytree for ``params`` by first-match path rules.

    A matched spec is kept only if every named axis divides the corresponding
    param dimension (tiny test models fall back to replication rather than
    erroring out).
    """
    rules = DEFAULT_RULES if rules is None else tuple(rules)
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    axis_sizes = dict(mesh.shape) if mesh is not None else {}

    def spec_for(path, leaf):
        name = path_str(path)
        for pat, spec in compiled:
            if pat.search(name):
                return spec if _spec_fits(spec, leaf, axis_sizes) else P()
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _spec_fits(spec: P, leaf, axis_sizes: dict) -> bool:
    """True when every named axis of ``spec`` divides the matching dim."""
    if len(spec) > np.ndim(leaf):
        return False
    for dim, axis in enumerate(spec):
        if axis is None:
            continue
        size = axis_sizes.get(axis)
        if size and np.shape(leaf)[dim] % size != 0:
            return False
    return True


def shard_params(params: Any, mesh: Mesh,
                 rules: Optional[Rules] = None) -> Any:
    """Place ``params`` on the mesh according to the rules."""
    specs = partition_specs(params, rules, mesh)
    return mesh_lib.put_global(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(x, P)))


def build_pjit_epoch_fn(model, loss, tx: optax.GradientTransformation,
                        mesh: Mesh, metrics: Sequence[str] = (),
                        rules: Optional[Rules] = None,
                        dropout_seed: int = 0, accum_steps: int = 1,
                        precision: Optional[str] = None,
                        bucket_bytes: Optional[int] = None):
    """Sync data-parallel (× tensor-parallel) epoch: scan over staged steps.

    Returns ``(epoch_fn, place_state, place_data)``:
    - ``epoch_fn(state, data, step_offset) -> (state, metrics)`` — jitted,
      state donated; ``data`` leaves are [steps, batch, ...] with batch
      sharded over ``workers``.
    - ``place_state(state)`` / ``place_data(data)`` put pytrees on the mesh
      with the matching shardings.

    ``accum_steps > 1`` scans each step over that many microbatches
    (engine.make_accum_grad_fn), splitting the per-step batch on its leading
    axis — under GSPMD that axis is already sharded over ``workers``, so each
    device accumulates over its own rows and the psum stays once per
    optimizer step.

    ``precision`` selects a PrecisionPolicy for the loss-scaling side of the
    grad fns (the model's own ``precision`` field governs its compute; the
    trainer stamps both from one knob). With a guard-wrapped optimizer the
    step reads the live scale out of ``opt_state``; otherwise the static
    policy scale applies.

    ``bucket_bytes`` switches the step from GSPMD's implicit grad
    all-reduce to an EXPLICIT shard_map data-parallel step whose gradient
    psums are issued per size-targeted bucket (parallel/collectives.py), so
    each bucket's all-reduce overlaps the rest of backward. Explicit
    collectives and GSPMD's model-axis collectives do not compose, so this
    mode requires a pure data-parallel mesh (``model`` axis of size 1).

    This is the honest sync-DP fast path (BASELINE config 5): one compiled
    program, grads all-reduced by GSPMD, params optionally model-sharded.
    """
    metric_names = tuple(metrics)
    accum_steps = int(accum_steps)
    if accum_steps > 1:
        grad_fn = engine.make_accum_grad_fn(model, loss, accum_steps,
                                            metric_names, precision=precision)
    else:
        grad_fn = engine.make_grad_fn(model, loss, precision=precision)
    base_key = jax.random.key(dropout_seed)
    num_workers = mesh.shape[mesh_lib.WORKER_AXIS]
    if bucket_bytes is not None and mesh.shape.get(mesh_lib.MODEL_AXIS, 1) > 1:
        raise ValueError(
            f"bucket_bytes={bucket_bytes} requests explicit bucketed grad "
            f"all-reduce, which requires a pure data-parallel mesh; this "
            f"mesh shards the model axis over "
            f"{mesh.shape[mesh_lib.MODEL_AXIS]} devices (GSPMD's implicit "
            f"model-parallel collectives do not compose with explicit "
            f"shard_map psums — drop bucket_bytes or use model=1)")

    def one_step_body(st, batch, rng, fold):
        """Shared step body; ``fold(loss, grads, aux, batch)`` injects the
        cross-worker reduction (identity under GSPMD, bucketed psum under
        shard_map)."""
        scale = precision_lib.current_scale(st.opt_state)
        (loss_val, aux), grads = grad_fn(st.params, batch,
                                         {"dropout": rng},
                                         loss_scale=scale)
        loss_val, grads, metric_out = fold(loss_val, grads, aux, batch)
        updates, opt_state = tx.update(grads, st.opt_state, st.params)
        params = optax.apply_updates(st.params, updates)
        out = {"loss": loss_val}
        out.update(metric_out)
        return engine.TrainState(step=st.step + 1, params=params,
                                 opt_state=opt_state), out

    def gspmd_fold(loss_val, grads, aux, batch):
        out = {}
        for name in metric_names:
            if accum_steps > 1:
                out[name] = engine.finalize_metric(aux[name])
            else:
                out[name] = engine.compute_metric(name, aux,
                                                  batch["labels"])
        return loss_val, grads, out

    def bucketed_fold(loss_val, grads, aux, batch):
        # per-shard means over equal-sized shards: pmean == global mean
        grads = collectives.bucketed_psum(grads, mesh_lib.WORKER_AXIS,
                                          bucket_bytes)
        grads = jax.tree.map(lambda g: g / num_workers, grads)
        loss_val = jax.lax.pmean(loss_val, mesh_lib.WORKER_AXIS)
        out = {}
        for name in metric_names:
            if accum_steps > 1:
                # (num, den) terms sum exactly across workers
                out[name] = engine.finalize_metric(
                    jax.lax.psum(aux[name], mesh_lib.WORKER_AXIS))
            else:
                out[name] = jax.lax.pmean(
                    engine.compute_metric(name, aux, batch["labels"]),
                    mesh_lib.WORKER_AXIS)
        return loss_val, grads, out

    def make_epoch(fold, decorrelate_rng):
        def epoch(state, data, step_offset):
            def one_step(st, xs):
                batch, i = xs
                rng = jax.random.fold_in(base_key, step_offset + i)
                if decorrelate_rng:
                    rng = jax.random.fold_in(
                        rng, jax.lax.axis_index(mesh_lib.WORKER_AXIS))
                return one_step_body(st, batch, rng, fold)

            steps = jax.tree.leaves(data)[0].shape[0]
            idx = jnp.arange(steps, dtype=jnp.int32)
            return jax.lax.scan(one_step, state, (data, idx))
        return epoch

    if bucket_bytes is None:
        epoch = make_epoch(gspmd_fold, decorrelate_rng=False)
    else:
        epoch = shard_map(
            make_epoch(bucketed_fold, decorrelate_rng=True),
            mesh=mesh,
            in_specs=(P(), P(None, mesh_lib.WORKER_AXIS), P()),
            out_specs=(P(), P()))

    data_sharding = NamedSharding(mesh, P(None, mesh_lib.WORKER_AXIS))

    def place_state(state):
        # Optimizer-state subtrees that mirror the param tree (adam's mu/nu,
        # momentum buffers — optax states are params-shaped pytrees) take the
        # params' shardings STRUCTURALLY, leaf for leaf — otherwise TP's
        # memory savings are lost to replicated 2x-param optimizer state.
        # Matching by tree structure (not leaf shape) keeps two same-shaped,
        # differently-sharded params from colliding onto one spec.
        specs = partition_specs(state.params, rules, mesh)
        param_treedef = jax.tree.structure(state.params)
        axis_sizes = dict(mesh.shape)
        is_spec = lambda x: isinstance(x, P)

        def params_like(sub):
            try:
                return jax.tree.structure(sub) == param_treedef
            except Exception:
                return False

        def opt_subtree_shardings(sub):
            if params_like(sub):
                return jax.tree.map(
                    lambda spec, leaf: NamedSharding(
                        mesh,
                        spec if _spec_fits(spec, leaf, axis_sizes) else P()),
                    specs, sub, is_leaf=is_spec)
            return jax.tree.map(lambda _: NamedSharding(mesh, P()), sub)

        return engine.TrainState(
            step=mesh_lib.put_global(state.step, NamedSharding(mesh, P())),
            params=shard_params(state.params, mesh, rules),
            opt_state=mesh_lib.put_global(
                state.opt_state,
                jax.tree.map(opt_subtree_shardings, state.opt_state,
                             is_leaf=params_like)))

    def place_data(data):
        return mesh_lib.put_global(data, data_sharding)

    epoch_fn = jax.jit(epoch, donate_argnums=(0,))
    return epoch_fn, place_state, place_data


def stage_steps(dataset, features_col: str, label_col: str, batch_size: int,
                max_steps: Optional[int] = None) -> tuple:
    """[steps, batch, ...] arrays from a Dataset (global batch; the mesh
    shards the batch dim over workers at device_put). Whole-epoch-resident;
    see :func:`stage_step_chunks` for O(chunk) staging."""
    n = len(dataset)
    steps = n // batch_size
    if max_steps is not None:
        steps = min(steps, max_steps)
    if steps == 0:
        raise ValueError(f"{n} rows cannot form one batch of {batch_size}")
    cut = steps * batch_size

    def stack(col):
        arr = np.asarray(dataset[col][:cut])
        return arr.reshape((steps, batch_size) + arr.shape[1:])

    return {"features": stack(features_col),
            "labels": stack(label_col)}, steps


def stage_step_chunks(dataset, features_col: str, label_col: str,
                      batch_size: int, chunk_steps: Optional[int] = None,
                      max_steps: Optional[int] = None):
    """Yield ``(host_data, steps)`` chunks of at most ``chunk_steps`` steps,
    keeping staging memory O(chunk) instead of O(epoch). The caller places
    each chunk with the epoch fn's ``place_data`` (an async ``device_put``),
    so staging chunk *i+1* overlaps compute on chunk *i*. The final chunk
    may be ragged (one extra compilation)."""
    n = len(dataset)
    steps = n // batch_size
    if max_steps is not None:
        steps = min(steps, max_steps)
    if steps == 0:
        raise ValueError(f"{n} rows cannot form one batch of {batch_size}")
    if chunk_steps is None:
        chunk_steps = steps
    # columns stay lazy (views/memmaps/ShardedColumns); materialize per
    # chunk so file-backed datasets stream from disk in O(chunk) pieces
    arrs = {"features": dataset[features_col],
            "labels": dataset[label_col]}
    for start in range(0, steps, chunk_steps):
        cnt = min(chunk_steps, steps - start)
        lo = start * batch_size
        hi = lo + cnt * batch_size
        yield {key: np.asarray(a[lo:hi]).reshape(
                   (cnt, batch_size) + tuple(a.shape[1:]))
               for key, a in arrs.items()}, cnt
