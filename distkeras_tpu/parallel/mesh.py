"""Device mesh construction — the cluster abstraction.

Reference parity: dist-keras's "cluster" is Spark executors plus a driver
socket (``distkeras/networking.py`` host/port discovery — unverified, mount
empty). Here the cluster is a ``jax.sharding.Mesh``: the ``workers`` axis
carries data-parallel replicas (one per chip or per chip-group), and an
optional ``model`` axis is reserved for tensor-sharded large models. ICI/DCN
topology is XLA's problem; collectives ride the mesh axes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"
MODEL_AXIS = "model"


def make_mesh(num_workers: Optional[int] = None,
              model_parallelism: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (workers, model) mesh over available devices.

    ``num_workers=None`` uses every device for data parallelism — the analogue
    of the reference defaulting num_workers to the executor count.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_workers is None:
        num_workers = len(devices) // model_parallelism
    need = num_workers * model_parallelism
    if need > len(devices):
        raise ValueError(
            f"Mesh needs {need} devices ({num_workers} workers x "
            f"{model_parallelism} model shards) but only {len(devices)} "
            f"are visible")
    grid = np.asarray(devices[:need]).reshape(num_workers, model_parallelism)
    return Mesh(grid, (WORKER_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def worker_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading axis over workers, replicate the rest."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def round_major_sharded(mesh: Mesh) -> NamedSharding:
    """Shard axis 1 (workers) of round-major staged data.

    Epoch data is staged as (rounds, workers, window, batch, ...) — rounds
    leading, exactly the layout ``lax.scan`` consumes — so the device never
    materializes a transposed copy of the whole chunk (it would, briefly
    doubling data HBM, if staging were worker-major)."""
    return NamedSharding(mesh, P(None, WORKER_AXIS))


def put_global(tree, sharding):
    """Place host data onto a (possibly multi-process) mesh.

    ``sharding`` is one ``NamedSharding`` for every leaf, or a pytree of
    shardings matching ``tree``. Single process: plain ``device_put``.
    Multi-process (a mesh spanning the coordination service's global
    devices): every process must hold the SAME full host array —
    deterministic init / identical datasets, the contract the reference
    met by broadcasting from the Spark driver — and each materializes only
    the shards addressable to it via ``make_array_from_callback``.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def put(x, sh):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx: x[idx])

    if isinstance(sharding, NamedSharding):
        return jax.tree.map(lambda x: put(x, sharding), tree)
    return jax.tree.map(put, tree, sharding)


def local_worker_positions(mesh: Mesh) -> list:
    """Worker-axis positions with at least one device owned by this process.

    Under the host-sharded data contract each process stages data only for
    these positions (its "addressable workers") — the TPU-native analogue of
    a Spark executor reading only its partitions. With one process this is
    every position, so host-sharded staging degrades to the ordinary case.
    """
    pi = jax.process_index()
    grid = mesh.devices  # (workers, model, ...)
    return [w for w in range(grid.shape[0])
            if any(d.process_index == pi for d in np.ravel(grid[w]))]


def put_host_sharded(tree_local, sharding: NamedSharding,
                     mesh_workers: int, local_positions: Sequence[int]):
    """Place round-major data (axis 1 = workers) when this process holds
    ONLY its own workers' rows.

    ``mesh_workers`` is the worker AXIS size D (mesh positions, not logical
    workers). ``tree_local`` leaves are (rounds, len(local_positions)·f,
    ...) — this process's worker columns in ``local_positions`` order, each
    position contributing its ``f`` stacked logical workers
    (oversubscription factor, inferred from the local block; the global
    worker axis is then D·f logical workers). Every addressable device's
    shard is sliced out of the local block and placed with
    ``make_array_from_single_device_arrays`` — no process ever
    materializes another host's rows, unlike :func:`put_global` which
    requires the full array on every host.
    """
    local_positions = list(local_positions)

    def put(x_local):
        n_local = x_local.shape[1]
        if n_local % len(local_positions):
            raise ValueError(
                f"local data axis 1 ({n_local}) must be a multiple of the "
                f"local position count ({len(local_positions)})")
        factor = n_local // len(local_positions)
        global_axis1 = mesh_workers * factor
        col_of = {}  # global logical worker -> local column
        for i, w in enumerate(local_positions):
            for j in range(factor):
                col_of[w * factor + j] = i * factor + j
        shape = (x_local.shape[0], global_axis1) + x_local.shape[2:]
        arrays = []
        for d, idx in sharding.addressable_devices_indices_map(shape).items():
            sl = idx[1]  # this device's worker-axis slice
            lo = sl.start or 0
            hi = sl.stop if sl.stop is not None else global_axis1
            try:
                cols = [col_of[g] for g in range(lo, hi)]
            except KeyError as e:
                raise ValueError(
                    f"Device {d} needs logical worker {e.args[0]} but this "
                    f"process staged only positions {local_positions}; "
                    f"host-sharded staging requires each process to provide "
                    f"all its addressable workers' shards") from None
            if not cols:
                raise ValueError(
                    f"Device {d} has an EMPTY worker-axis slice {sl!r} under "
                    f"sharding {sharding} (global axis {global_axis1}); a "
                    f"degenerate sharding that assigns a device no workers "
                    f"cannot be host-staged")
            block = x_local[:, cols] if cols != list(
                range(cols[0], cols[0] + len(cols))) else \
                x_local[:, cols[0]:cols[0] + len(cols)]
            arrays.append(jax.device_put(block, d))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)

    return jax.tree.map(put, tree_local)


def put_replicated(tree, mesh: Mesh):
    return put_global(tree, replicated(mesh))


def put_worker_sharded(tree, mesh: Mesh):
    """Place a pytree whose leaves all have a leading ``workers`` axis."""
    return put_global(tree, worker_sharded(mesh))
