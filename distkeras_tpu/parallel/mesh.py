"""Device mesh construction — the cluster abstraction.

Reference parity: dist-keras's "cluster" is Spark executors plus a driver
socket (``distkeras/networking.py`` host/port discovery — unverified, mount
empty). Here the cluster is a ``jax.sharding.Mesh``: the ``workers`` axis
carries data-parallel replicas (one per chip or per chip-group), and an
optional ``model`` axis is reserved for tensor-sharded large models. ICI/DCN
topology is XLA's problem; collectives ride the mesh axes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"
MODEL_AXIS = "model"


def make_mesh(num_workers: Optional[int] = None,
              model_parallelism: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (workers, model) mesh over available devices.

    ``num_workers=None`` uses every device for data parallelism — the analogue
    of the reference defaulting num_workers to the executor count.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_workers is None:
        num_workers = len(devices) // model_parallelism
    need = num_workers * model_parallelism
    if need > len(devices):
        raise ValueError(
            f"Mesh needs {need} devices ({num_workers} workers x "
            f"{model_parallelism} model shards) but only {len(devices)} "
            f"are visible")
    grid = np.asarray(devices[:need]).reshape(num_workers, model_parallelism)
    return Mesh(grid, (WORKER_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def worker_sharded(mesh: Mesh) -> NamedSharding:
    """Shard the leading axis over workers, replicate the rest."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def round_major_sharded(mesh: Mesh) -> NamedSharding:
    """Shard axis 1 (workers) of round-major staged data.

    Epoch data is staged as (rounds, workers, window, batch, ...) — rounds
    leading, exactly the layout ``lax.scan`` consumes — so the device never
    materializes a transposed copy of the whole chunk (it would, briefly
    doubling data HBM, if staging were worker-major)."""
    return NamedSharding(mesh, P(None, WORKER_AXIS))


def put_global(tree, sharding):
    """Place host data onto a (possibly multi-process) mesh.

    ``sharding`` is one ``NamedSharding`` for every leaf, or a pytree of
    shardings matching ``tree``. Single process: plain ``device_put``.
    Multi-process (a mesh spanning the coordination service's global
    devices): every process must hold the SAME full host array —
    deterministic init / identical datasets, the contract the reference
    met by broadcasting from the Spark driver — and each materializes only
    the shards addressable to it via ``make_array_from_callback``.
    """
    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)

    def put(x, sh):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx: x[idx])

    if isinstance(sharding, NamedSharding):
        return jax.tree.map(lambda x: put(x, sharding), tree)
    return jax.tree.map(put, tree, sharding)


def put_replicated(tree, mesh: Mesh):
    return put_global(tree, replicated(mesh))


def put_worker_sharded(tree, mesh: Mesh):
    """Place a pytree whose leaves all have a leading ``workers`` axis."""
    return put_global(tree, worker_sharded(mesh))
