"""Sharded parameter-server fleet: split the center, survive the churn.

DESIGN.md §6 called the single ParameterServerService the honest
limitation of the cross-process path: one process, one socket, the whole
center pytree through one NIC. This module removes it (DESIGN.md §13):

- :func:`shard_assignment` splits the center's LEAVES over N shards with
  a deterministic size-balanced greedy packing — every process computes
  the identical map from its own (identically-initialized) params, so the
  map never travels;
- each shard is an ordinary :class:`ParameterServerService` over an
  ordinary ParameterServer holding just its leaf subset (a python list of
  leaves IS a pytree — the codec/chunking/auth stack is reused unchanged,
  and N=1 is wire-identical to the single-server protocol);
- :class:`ShardedRemoteParameterServer` fans pull/commit out in parallel
  and reassembles, presenting the same ParameterServer interface, so
  HostAsyncRunner cannot tell a fleet from a single server.

Consistency model (the paper's, made explicit): shard 0 is the
**coordinator** — its clock is the authority a pull reports and the
membership/lease/history plane lives there. A logical commit folds on
the coordinator FIRST; the coordinator's reply carries the applied fold
weight, and every follower shard folds the same commit with that exact
explicit weight — so a DynSGD fold scales identically on all shards even
though their local clocks never talk to each other. A pull reads shards
concurrently and may observe a commit on one shard before another (a
torn read); under ASYNCHRONOUS SGD that is one more staleness
perturbation of the same kind the algorithm already absorbs, and it
vanishes at the quiescent points where equality matters (history
barrier, final center).
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.health.heartbeat import StragglerDetector
from distkeras_tpu.health.membership import DEFAULT_LEASE_S, Membership
from distkeras_tpu.parallel.remote_ps import (
    ParameterServerService,
    RemoteParameterServer,
)
from distkeras_tpu.utils.fetch import device_get_batched


def shard_assignment(like: Any, num_shards: int) -> list:
    """Deterministic size-balanced leaf→shard map: greedy longest-
    processing-time packing (leaves by descending byte size, each to the
    currently lightest shard; all ties broken by index, so every process
    computes the same map). Returns ``num_shards`` sorted index lists.
    """
    leaves = jax.tree_util.tree_flatten(like)[0]
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if num_shards > len(leaves):
        raise ValueError(
            f"cannot split {len(leaves)} leaves over {num_shards} shards "
            f"(a shard would hold no parameters)")
    sizes = [int(np.prod(np.shape(l)) * np.dtype(
        getattr(l, "dtype", np.float32)).itemsize) for l in leaves]
    order = sorted(range(len(leaves)), key=lambda i: (-sizes[i], i))
    loads = [0] * num_shards
    shards: list = [[] for _ in range(num_shards)]
    for i in order:
        s = min(range(num_shards), key=lambda j: (loads[j], j))
        shards[s].append(i)
        loads[s] += sizes[i]
    return [sorted(s) for s in shards]


def split_tree(tree: Any, assignment: Sequence[Sequence[int]]) -> list:
    """The tree's leaves regrouped per shard (each group is itself a
    pytree — a python list — so the per-shard codec stack is unchanged)."""
    leaves = jax.tree_util.tree_flatten(tree)[0]
    return [[leaves[i] for i in idxs] for idxs in assignment]


def join_tree(parts: Sequence[Sequence], assignment, treedef) -> Any:
    """Inverse of :func:`split_tree`: reassemble the full pytree."""
    leaves: list = [None] * sum(len(idxs) for idxs in assignment)
    for part, idxs in zip(parts, assignment):
        for leaf, i in zip(part, idxs):
            leaves[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, leaves)


def make_ps_fleet(ps_factory: Callable[[Any], Any], like: Any,
                  num_shards: int, expected_processes: int = 1,
                  host: str = "0.0.0.0", token: Optional[str] = None,
                  codecs: Optional[Sequence[str]] = None,
                  advertise_host: str = "127.0.0.1",
                  lease_s: float = DEFAULT_LEASE_S,
                  straggler: Optional[StragglerDetector] = None,
                  time_fn: Callable[[], float] = time.time) -> list:
    """Construct and start N shard services on this host.

    ``ps_factory`` builds the server flavor for one shard's leaf list
    (e.g. ``DynSGDParameterServer``). Shard 0 gets the membership plane
    (leases + straggler-driven eviction); followers hold only leaves.
    Every service is started and knows the full fleet map
    (``shard_addresses``), so any shard can bootstrap a late joiner.
    """
    assignment = shard_assignment(like, num_shards)
    parts = split_tree(like, assignment)
    services = []
    for shard, part in enumerate(parts):
        membership = Membership(lease_s=lease_s, straggler=straggler,
                                time_fn=time_fn) if shard == 0 else None
        services.append(ParameterServerService(
            ps_factory(part), part, expected_processes=expected_processes,
            host=host, token=token, codecs=codecs, membership=membership,
            shard=shard, num_shards=num_shards))
    addresses = [f"{advertise_host}:{svc.port}" for svc in services]
    for svc in services:
        svc.shard_addresses = addresses
        svc.start()
    return services


class ShardedRemoteParameterServer:
    """Client for a shard fleet — a drop-in for the ParameterServer
    interface, exactly like :class:`RemoteParameterServer` is for one
    server (which is also what this degenerates to at N=1, one object
    deep).

    Pulls and follower commits fan out on a small thread pool; commit
    identity (one ``(cid, seq)`` per LOGICAL commit, shared by all its
    shard legs and all their retries) comes from the coordinator client,
    so a retried multi-shard commit dedups per shard and folds once
    everywhere.
    """

    elastic = True

    def __init__(self, addresses: Sequence[str], like: Any,
                 timeout: float = 600.0, token: Optional[str] = None,
                 codec: str = "raw", retry=None,
                 op_timeout: Optional[float] = None):
        addresses = list(addresses)
        if not addresses:
            raise ValueError("need at least one shard address")
        self.assignment = shard_assignment(like, len(addresses))
        host_tree = jax.tree.map(np.asarray, device_get_batched(like))
        self._treedef = jax.tree_util.tree_flatten(host_tree)[1]
        parts = split_tree(host_tree, self.assignment)
        self.clients = [
            RemoteParameterServer(addr, part, timeout=timeout, token=token,
                                  codec=codec, retry=retry,
                                  op_timeout=op_timeout)
            for addr, part in zip(addresses, parts)]
        for client in self.clients[1:]:
            client.cid = self.clients[0].cid  # one commit identity
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(4, 2 * len(self.clients)),
            thread_name_prefix="ps-shard")

    @property
    def coordinator(self) -> RemoteParameterServer:
        return self.clients[0]

    # -- ParameterServer interface ----------------------------------------
    def pull(self):
        futures = [self._pool.submit(c.pull) for c in self.clients]
        results = [f.result() for f in futures]
        # clock authority is the coordinator; follower clocks only order
        # their own folds (see the torn-read note in the module docstring)
        return (join_tree([r[0] for r in results], self.assignment,
                          self._treedef), results[0][1])

    def commit(self, delta: Any, last_update: int = 0, **kw) -> int:
        return self.commit_ex(delta, last_update=last_update, **kw)[0]

    def commit_ex(self, delta: Any, last_update: int = 0, weight=None,
                  seq: Optional[int] = None, worker: Optional[int] = None,
                  window_s: Optional[float] = None) -> tuple:
        parts = split_tree(delta, self.assignment)
        if seq is None:
            seq = self.clients[0].next_seq()
        # the fan-out is the trace's branching point: the caller's commit
        # span is the parent, each shard leg a child. Pool threads do not
        # inherit thread-local context, so follower legs adopt it
        # explicitly (None when the commit is untraced — plain path).
        ctx = telemetry.current_trace()
        # coordinator first: its fold fixes the authoritative weight (and
        # runs the membership plane — late folds, lease renewal); every
        # follower then folds the same commit at that explicit weight
        with telemetry.span("trace.shard", shard=0):
            at_fold, applied = self.clients[0].commit_ex(
                parts[0], last_update=last_update, weight=weight, seq=seq,
                worker=worker, window_s=window_s)
        futures = [
            self._pool.submit(self._shard_leg, ctx, i, c, part,
                              last_update, applied, seq)
            for i, (c, part) in enumerate(
                zip(self.clients[1:], parts[1:]), start=1)]
        for f in futures:
            f.result()
        return at_fold, applied

    @staticmethod
    def _shard_leg(ctx, shard, client, part, last_update, applied, seq):
        with telemetry.use_trace(ctx):
            with telemetry.span("trace.shard", shard=shard):
                return client.commit_ex(part, last_update, applied, seq)

    @property
    def num_updates(self) -> int:
        return self.clients[0].num_updates

    # membership/history live on the coordinator shard
    def register(self, worker: int,
                 lease_s: Optional[float] = None) -> float:
        return self.clients[0].register(worker, lease_s=lease_s)

    def renew_lease(self, worker: int) -> bool:
        return self.clients[0].renew_lease(worker)

    def deregister(self, worker: int) -> None:
        self.clients[0].deregister(worker)

    def shard_map(self) -> dict:
        return self.clients[0].shard_map()

    def put_history(self, pid: int, windows: list) -> None:
        self.clients[0].put_history(pid, windows)

    # the telemetry collector also lives on the coordinator shard
    def put_telemetry(self, pid: int, rows: list) -> dict:
        return self.clients[0].put_telemetry(pid, rows)

    def get_merged_telemetry(self) -> list:
        return self.clients[0].get_merged_telemetry()

    def get_history(self, timeout: float = 600):
        # the barrier (and merged history, and final clock) live on the
        # coordinator; the fleet is quiescent once it resolves, so the
        # follower pulls below read a settled center
        windows, part0, clock = self.clients[0].get_history(timeout=timeout)
        futures = [self._pool.submit(c.pull) for c in self.clients[1:]]
        parts = [part0] + [f.result()[0] for f in futures]
        return (windows, join_tree(parts, self.assignment, self._treedef),
                clock)

    def close(self) -> None:
        for client in self.clients:
            client.close()  # idempotent, bounded
        self._pool.shutdown(wait=False)

    # reference lifecycle no-ops (parity with ParameterServer)
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass
