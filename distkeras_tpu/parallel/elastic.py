"""Sharded parameter-server fleet: split the center, survive the churn.

DESIGN.md §6 called the single ParameterServerService the honest
limitation of the cross-process path: one process, one socket, the whole
center pytree through one NIC. This module removes it (DESIGN.md §13):

- :func:`shard_assignment` splits the center's LEAVES over N shards with
  a deterministic size-balanced greedy packing — every process computes
  the identical map from its own (identically-initialized) params, so the
  map never travels;
- each shard is an ordinary :class:`ParameterServerService` over an
  ordinary ParameterServer holding just its leaf subset (a python list of
  leaves IS a pytree — the codec/chunking/auth stack is reused unchanged,
  and N=1 is wire-identical to the single-server protocol);
- :class:`ShardedRemoteParameterServer` fans pull/commit out in parallel
  and reassembles, presenting the same ParameterServer interface, so
  HostAsyncRunner cannot tell a fleet from a single server.

Consistency model (the paper's, made explicit): shard 0 is the
**coordinator** — its clock is the authority a pull reports and the
membership/lease/history plane lives there. A logical commit folds on
the coordinator FIRST; the coordinator's reply carries the applied fold
weight, and every follower shard folds the same commit with that exact
explicit weight — so a DynSGD fold scales identically on all shards even
though their local clocks never talk to each other. A pull reads shards
concurrently and may observe a commit on one shard before another (a
torn read); under ASYNCHRONOUS SGD that is one more staleness
perturbation of the same kind the algorithm already absorbs, and it
vanishes at the quiescent points where equality matters (history
barrier, final center).
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.health.heartbeat import StragglerDetector
from distkeras_tpu.health.membership import DEFAULT_LEASE_S, Membership
from distkeras_tpu.parallel import failover
from distkeras_tpu.parallel.remote_ps import (
    CoordinatorFenced,
    ParameterServerService,
    PSUnavailable,
    RemoteParameterServer,
)
from distkeras_tpu.utils.fetch import device_get_batched

#: shard→process placement policies (DESIGN.md §17): "process0" is the
#: historical layout (every shard on process 0's host — fan-out buys
#: socket/codec/fold parallelism, not NIC aggregation); "spread" deals
#: shards round-robin over processes so the fleet aggregates NICs and
#: survives single-host loss.
PLACEMENT_POLICIES = ("process0", "spread")


def shard_placement(num_shards: int, num_processes: int,
                    policy: str = "process0") -> list:
    """Deterministic shard→hosting-process map; every process computes
    the identical map from the same (num_shards, num_processes, policy),
    so the map itself never travels — only the resulting addresses do.
    "spread" degenerates to all-on-0 at one process."""
    if policy not in PLACEMENT_POLICIES:
        raise ValueError(f"ps_placement must be one of "
                         f"{PLACEMENT_POLICIES}, got {policy!r}")
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if policy == "process0" or int(num_processes) <= 1:
        return [0] * num_shards
    return [s % int(num_processes) for s in range(num_shards)]


def standby_process(placement: Sequence[int]) -> int:
    """Which process hosts the coordinator's standby: shard 1's process —
    a DIFFERENT host than the coordinator whenever the placement spreads
    over >1 process, so the standby survives the coordinator's host
    dying. Single-shard (or process0) fleets fall back to the
    coordinator's own process: the standby then still survives service
    death, just not host death."""
    placement = list(placement)
    return placement[1] if len(placement) > 1 else placement[0]


def shard_assignment(like: Any, num_shards: int) -> list:
    """Deterministic size-balanced leaf→shard map: greedy longest-
    processing-time packing (leaves by descending byte size, each to the
    currently lightest shard; all ties broken by index, so every process
    computes the same map). Returns ``num_shards`` sorted index lists.
    """
    leaves = jax.tree_util.tree_flatten(like)[0]
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if num_shards > len(leaves):
        raise ValueError(
            f"cannot split {len(leaves)} leaves over {num_shards} shards "
            f"(a shard would hold no parameters)")
    sizes = [int(np.prod(np.shape(l)) * np.dtype(
        getattr(l, "dtype", np.float32)).itemsize) for l in leaves]
    order = sorted(range(len(leaves)), key=lambda i: (-sizes[i], i))
    loads = [0] * num_shards
    shards: list = [[] for _ in range(num_shards)]
    for i in order:
        s = min(range(num_shards), key=lambda j: (loads[j], j))
        shards[s].append(i)
        loads[s] += sizes[i]
    return [sorted(s) for s in shards]


def split_tree(tree: Any, assignment: Sequence[Sequence[int]]) -> list:
    """The tree's leaves regrouped per shard (each group is itself a
    pytree — a python list — so the per-shard codec stack is unchanged)."""
    leaves = jax.tree_util.tree_flatten(tree)[0]
    return [[leaves[i] for i in idxs] for idxs in assignment]


def join_tree(parts: Sequence[Sequence], assignment, treedef) -> Any:
    """Inverse of :func:`split_tree`: reassemble the full pytree."""
    leaves: list = [None] * sum(len(idxs) for idxs in assignment)
    for part, idxs in zip(parts, assignment):
        for leaf, i in zip(part, idxs):
            leaves[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, leaves)


def make_ps_fleet(ps_factory: Callable[[Any], Any], like: Any,
                  num_shards: int, expected_processes: int = 1,
                  host: str = "0.0.0.0", token: Optional[str] = None,
                  codecs: Optional[Sequence[str]] = None,
                  advertise_host: str = "127.0.0.1",
                  lease_s: float = DEFAULT_LEASE_S,
                  straggler: Optional[StragglerDetector] = None,
                  time_fn: Callable[[], float] = time.time,
                  local_shards: Optional[Sequence[int]] = None,
                  standby: bool = False,
                  coord_lease_s: float = failover.DEFAULT_COORD_LEASE_S,
                  start: bool = True) -> list:
    """Construct (and by default wire + start) shard services on this host.

    ``ps_factory`` builds the server flavor for one shard's leaf list
    (e.g. ``DynSGDParameterServer``). Shard 0 gets the membership plane
    (leases + straggler-driven eviction); followers hold only leaves.

    ``local_shards`` selects WHICH shards this process hosts (None = all
    of them — the historical single-host fleet). With a partial set the
    services come back bound-but-unstarted regardless of ``start``: the
    launcher must gather the cross-host address map first and finish via
    :func:`connect_fleet` (see ``run_cross_process``'s spread placement).

    ``standby=True`` appends a DARK standby service (LAST in the returned
    list, so ``services[0]`` stays the coordinator when it is local and
    blanket ``stop()`` loops keep working): a full service over a
    shard-0 replica built by the same factory, serving only the
    replication/discovery/health plane until its
    :class:`~distkeras_tpu.parallel.failover.StandbyState` promotes.
    """
    assignment = shard_assignment(like, num_shards)
    parts = split_tree(like, assignment)
    which = list(range(num_shards)) if local_shards is None \
        else sorted(int(s) for s in local_shards)
    services = []
    for shard in which:
        part = parts[shard]
        membership = Membership(lease_s=lease_s, straggler=straggler,
                                time_fn=time_fn) if shard == 0 else None
        svc = ParameterServerService(
            ps_factory(part), part, expected_processes=expected_processes,
            host=host, token=token, codecs=codecs, membership=membership,
            shard=shard, num_shards=num_shards)
        svc.advertised = f"{advertise_host}:{svc.port}"
        services.append(svc)
    if standby:
        # the standby replicates the COORDINATOR: same shard-0 leaf
        # subset, same server flavor (same start clock via the factory),
        # so replayed folds land on a bit-identical replica
        svc = ParameterServerService(
            ps_factory(parts[0]), parts[0],
            expected_processes=expected_processes, host=host,
            token=token, codecs=codecs, membership=None, shard=0,
            num_shards=num_shards)
        svc.advertised = f"{advertise_host}:{svc.port}"
        svc.is_standby = True
        svc.standby = failover.StandbyState(
            svc, lease_s=coord_lease_s, member_lease_s=lease_s,
            straggler=straggler, time_fn=time_fn)
        services.append(svc)
    if start and local_shards is None:
        addresses = [svc.advertised for svc in services
                     if not svc.is_standby]
        standby_addr = next((svc.advertised for svc in services
                             if svc.is_standby), None)
        connect_fleet(services, addresses, standby_address=standby_addr,
                      token=token, coord_lease_s=coord_lease_s,
                      time_fn=time_fn)
    return services


def connect_fleet(services: Sequence, addresses: Sequence[str],
                  standby_address: Optional[str] = None, *,
                  token: Optional[str] = None,
                  coord_lease_s: float = failover.DEFAULT_COORD_LEASE_S,
                  time_fn: Callable[[], float] = time.time) -> None:
    """Wire this process's (possibly partial) services into one fleet and
    start them: every service learns the full shard map + standby
    address, and a locally-hosted coordinator gets its
    :class:`~distkeras_tpu.parallel.failover.Replicator` streaming
    clock/membership/commits to the standby."""
    addresses = list(addresses)
    for svc in services:
        svc.shard_addresses = addresses
        svc.standby_address = standby_address
        svc.start()
    if standby_address is None:
        return
    for svc in services:
        if svc.shard == 0 and not svc.is_standby:
            rep = failover.Replicator(
                standby_address, token=token, lease_s=coord_lease_s,
                members_fn=(svc.membership.export
                            if svc.membership is not None else None),
                clock_fn=lambda s=svc: int(s.ps.num_updates),
                on_fenced=lambda epoch, s=svc: s.fence(epoch),
                time_fn=time_fn)
            svc.replicator = rep
            rep.start()


def gather_fleet_addresses(services: Sequence, num_shards: int) -> tuple:
    """All-gather every process's locally-hosted shard addresses into the
    complete fleet map. Returns ``(addresses, standby_address)`` —
    identical on every process. Single-process: a pure local reshuffle,
    no collective."""
    local = {("standby" if svc.is_standby else int(svc.shard)):
             svc.advertised for svc in services}
    if jax.process_count() == 1:
        return ([local[s] for s in range(num_shards)],
                local.get("standby"))
    from jax.experimental import multihost_utils
    msg = ";".join(f"{k}={v}" for k, v in sorted(
        local.items(), key=lambda kv: str(kv[0])))
    payload = np.zeros((512,), np.uint8)
    raw = msg.encode()
    if len(raw) > payload.size:
        raise ValueError(f"address payload {len(raw)}B exceeds "
                         f"{payload.size}B broadcast slot")
    payload[:len(raw)] = np.frombuffer(raw, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(payload))
    merged: dict = {}
    for row in gathered:
        text = bytes(np.asarray(row)[np.asarray(row) != 0]).decode()
        for entry in filter(None, text.split(";")):
            key, _, addr = entry.partition("=")
            merged[key] = addr
    missing = [s for s in range(num_shards) if str(s) not in merged]
    if missing:
        raise RuntimeError(f"fleet address gather incomplete: shards "
                           f"{missing} unhosted (map: {merged})")
    return ([merged[str(s)] for s in range(num_shards)],
            merged.get("standby"))


class ShardedRemoteParameterServer:
    """Client for a shard fleet — a drop-in for the ParameterServer
    interface, exactly like :class:`RemoteParameterServer` is for one
    server (which is also what this degenerates to at N=1, one object
    deep).

    Pulls and follower commits fan out on a small thread pool; commit
    identity (one ``(cid, seq)`` per LOGICAL commit, shared by all its
    shard legs and all their retries) comes from the coordinator client,
    so a retried multi-shard commit dedups per shard and folds once
    everywhere.
    """

    elastic = True

    def __init__(self, addresses: Sequence[str], like: Any,
                 timeout: float = 600.0, token: Optional[str] = None,
                 codec: str = "raw", retry=None,
                 op_timeout: Optional[float] = None,
                 standby: Optional[str] = None):
        addresses = list(addresses)
        if not addresses:
            raise ValueError("need at least one shard address")
        self.assignment = shard_assignment(like, len(addresses))
        host_tree = jax.tree.map(np.asarray, device_get_batched(like))
        self._treedef = jax.tree_util.tree_flatten(host_tree)[1]
        parts = split_tree(host_tree, self.assignment)
        self.clients = [
            RemoteParameterServer(addr, part, timeout=timeout, token=token,
                                  codec=codec, retry=retry,
                                  op_timeout=op_timeout)
            for addr, part in zip(addresses, parts)]
        for client in self.clients[1:]:
            client.cid = self.clients[0].cid  # one commit identity
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(4, 2 * len(self.clients)),
            thread_name_prefix="ps-shard")
        # coordinator failover (DESIGN.md §17): the standby's address and
        # everything needed to rebuild the coordinator leg against it
        self.standby_address = standby
        self.coord_epoch = 0
        self._parts = parts
        self._client_kw = dict(timeout=timeout, token=token, codec=codec,
                               retry=retry, op_timeout=op_timeout)
        self._failover_lock = threading.Lock()

    @property
    def coordinator(self) -> RemoteParameterServer:
        return self.clients[0]

    # -- coordinator re-resolution (DESIGN.md §17) -------------------------
    def _coord_call(self, fn):
        """Run a coordinator-leg operation; on a dead or fenced
        coordinator, re-resolve through the standby and retry ONCE. The
        original typed error propagates when re-resolution fails (or no
        standby is configured) — the host_async degradation ladder then
        takes over exactly as before failover existed."""
        client = self.clients[0]
        try:
            return fn(client)
        except (PSUnavailable, CoordinatorFenced) as e:
            if not self._re_resolve(client, e):
                raise
        return fn(self.clients[0])

    def _re_resolve(self, failed, err) -> bool:
        if self.standby_address is None:
            return False
        with self._failover_lock:
            if self.clients[0] is not failed:
                return True  # another thread already swapped the leg
            # a fenced reply names the promoted coordinator outright;
            # otherwise ask the standby (whose lease check IS the
            # failure detector — it promotes while answering)
            addr = getattr(err, "coordinator", None) or \
                self.standby_address
            fresh = None
            try:
                fresh = RemoteParameterServer(addr, self._parts[0],
                                              **self._client_kw)
                view = fresh.coordinator_view()
            except (PSUnavailable, RuntimeError, OSError):
                if fresh is not None:
                    fresh.close()
                return False
            if not view.get("promoted") or \
                    int(view.get("epoch", 0)) <= self.coord_epoch:
                # the lease has not lapsed yet (coordinator slow, not
                # dead) — keep degrading; a later window retries here
                fresh.close()
                return False
            old = self.clients[0]
            # commit identity continuity: the promoted coordinator's
            # replicated dedup mirror is keyed by the ORIGINAL (cid, seq)
            # stream, so the new leg keeps both
            fresh.cid = old.cid
            with old._seq_lock:
                fresh._seq = old._seq
            self.clients[0] = fresh
            self.coord_epoch = int(view["epoch"])
            old.close()
            telemetry.counter("elastic.failover.resolves").inc()
            telemetry.record_event("failover", transition="re_resolve",
                                   address=view.get("address", addr),
                                   epoch=self.coord_epoch)
            return True

    # -- ParameterServer interface ----------------------------------------
    def pull(self):
        futures = [self._pool.submit(self._coord_call,
                                     lambda c: c.pull())]
        futures += [self._pool.submit(c.pull) for c in self.clients[1:]]
        results = [f.result() for f in futures]
        # clock authority is the coordinator; follower clocks only order
        # their own folds (see the torn-read note in the module docstring)
        return (join_tree([r[0] for r in results], self.assignment,
                          self._treedef), results[0][1])

    def commit(self, delta: Any, last_update: int = 0, **kw) -> int:
        return self.commit_ex(delta, last_update=last_update, **kw)[0]

    def commit_ex(self, delta: Any, last_update: int = 0, weight=None,
                  seq: Optional[int] = None, worker: Optional[int] = None,
                  window_s: Optional[float] = None) -> tuple:
        parts = split_tree(delta, self.assignment)
        if seq is None:
            seq = self.clients[0].next_seq()
        # the fan-out is the trace's branching point: the caller's commit
        # span is the parent, each shard leg a child. Pool threads do not
        # inherit thread-local context, so follower legs adopt it
        # explicitly (None when the commit is untraced — plain path).
        ctx = telemetry.current_trace()
        # coordinator first: its fold fixes the authoritative weight (and
        # runs the membership plane — late folds, lease renewal); every
        # follower then folds the same commit at that explicit weight
        with telemetry.span("trace.shard", shard=0):
            at_fold, applied = self._coord_call(
                lambda c: c.commit_ex(
                    parts[0], last_update=last_update, weight=weight,
                    seq=seq, worker=worker, window_s=window_s))
        futures = [
            self._pool.submit(self._shard_leg, ctx, i, c, part,
                              last_update, applied, seq)
            for i, (c, part) in enumerate(
                zip(self.clients[1:], parts[1:]), start=1)]
        for f in futures:
            f.result()
        return at_fold, applied

    @staticmethod
    def _shard_leg(ctx, shard, client, part, last_update, applied, seq):
        with telemetry.use_trace(ctx):
            with telemetry.span("trace.shard", shard=shard):
                return client.commit_ex(part, last_update, applied, seq)

    @property
    def num_updates(self) -> int:
        return self._coord_call(lambda c: c.num_updates)

    # membership/history live on the coordinator shard
    def register(self, worker: int,
                 lease_s: Optional[float] = None) -> float:
        return self._coord_call(
            lambda c: c.register(worker, lease_s=lease_s))

    def renew_lease(self, worker: int) -> bool:
        return self._coord_call(lambda c: c.renew_lease(worker))

    def deregister(self, worker: int) -> None:
        self._coord_call(lambda c: c.deregister(worker))

    def shard_map(self) -> dict:
        return self._coord_call(lambda c: c.shard_map())

    def coordinator_view(self) -> dict:
        return self._coord_call(lambda c: c.coordinator_view())

    def put_history(self, pid: int, windows: list) -> None:
        self._coord_call(lambda c: c.put_history(pid, windows))

    # the telemetry collector also lives on the coordinator shard (and
    # follows it through a promotion — the standby re-mounts one)
    def put_telemetry(self, pid: int, rows: list) -> dict:
        return self._coord_call(lambda c: c.put_telemetry(pid, rows))

    def get_merged_telemetry(self) -> list:
        return self._coord_call(lambda c: c.get_merged_telemetry())

    def get_history(self, timeout: float = 600):
        # the barrier (and merged history, and final clock) live on the
        # coordinator; the fleet is quiescent once it resolves, so the
        # follower pulls below read a settled center
        windows, part0, clock = self._coord_call(
            lambda c: c.get_history(timeout=timeout))
        futures = [self._pool.submit(c.pull) for c in self.clients[1:]]
        parts = [part0] + [f.result()[0] for f in futures]
        return (windows, join_tree(parts, self.assignment, self._treedef),
                clock)

    def close(self) -> None:
        for client in self.clients:
            client.close()  # idempotent, bounded
        self._pool.shutdown(wait=False)

    # reference lifecycle no-ops (parity with ParameterServer)
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass
