"""Gradient-bucket collective overlap (DESIGN.md §11).

The sync substrate's data-parallel fold is ONE whole-tree ``psum`` issued
after the entire backward pass — the collective serializes behind compute.
Partitioning the gradient pytree into size-targeted buckets and issuing
one ``psum`` per bucket lets XLA's async collectives start each bucket's
all-reduce as soon as its leaves' backward segments complete, hiding
all-reduce latency behind the rest of backward (the compute-side twin of
PR 3's comms/compute overlap).

Bucketing is over the REVERSED flatten order: ``jax.tree`` flattening is
deterministic and roughly forward-topological (embedding/stem params
first, head last), so the reverse approximates backward completion order —
the first bucket to fire holds the leaves whose gradients finish first.

Exactness: ``jax.lax.psum`` applied to a tuple of leaves reduces each
leaf independently — the per-leaf sums are THE SAME operations whether
issued as one variadic psum or several, so ``bucketed_psum`` is
bitwise-equal to the whole-tree psum (asserted in tests/test_overlap.py,
including ragged tail buckets and the accum_steps composition).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np


def partition_buckets(nbytes: Sequence[int],
                      bucket_bytes: int) -> List[List[int]]:
    """Greedy size-targeted grouping of leaf indices, in REVERSED index
    order (≈ backward completion order; see module docstring).

    Each bucket accumulates leaves until it holds at least
    ``bucket_bytes``; the final (tail) bucket may be ragged — smaller than
    the target — rather than merged backward (merging would delay the
    last-to-complete leaves' collective, the opposite of the point).
    Every index appears exactly once; a leaf larger than the target gets
    its own bucket."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    buckets: List[List[int]] = []
    cur: List[int] = []
    size = 0
    for i in reversed(range(len(nbytes))):
        cur.append(i)
        size += int(nbytes[i])
        if size >= bucket_bytes:
            buckets.append(cur)
            cur, size = [], 0
    if cur:
        buckets.append(cur)  # ragged tail
    return buckets


def bucketed_psum(tree, axis_name, bucket_bytes: Optional[int] = None):
    """``jax.lax.psum(tree, axis_name)`` issued as one variadic psum per
    size-targeted bucket. ``bucket_bytes=None`` is exactly today's
    whole-tree psum (one collective)."""
    if bucket_bytes is None:
        return jax.lax.psum(tree, axis_name)
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    sizes = [int(np.prod(x.shape)) * x.dtype.itemsize for x in leaves]
    out: List = [None] * len(leaves)
    for idxs in partition_buckets(sizes, bucket_bytes):
        summed = jax.lax.psum(tuple(leaves[i] for i in idxs), axis_name)
        for i, s in zip(idxs, summed):
            out[i] = s
    return jax.tree.unflatten(treedef, out)
