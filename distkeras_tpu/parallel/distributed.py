"""Multi-host distributed backend — the networking.py replacement.

Reference parity: ``distkeras/networking.py`` (unverified, mount empty) is a
hand-rolled TCP layer — ``determine_host_address``, ``connect``,
``send_data``/``recv_data`` moving pickled dicts between Spark executors and
the driver's parameter-server socket. SURVEY.md §5 calls the swap: here the
"wire protocol" is XLA collectives compiled into the step (psum/all_gather
over ICI within a slice, DCN across slices), and the only host-level
networking is jax's coordination service, wrapped below.

Scaling model (How-to-Scale-Your-Model recipe): pick a mesh, annotate
shardings, let XLA insert collectives. ``multihost_mesh`` lays the
data-parallel ("workers") axis across slices/hosts so its all-reduces ride
DCN-friendly hierarchies, and keeps the model axis inside a slice where ICI
bandwidth lives.
"""

from __future__ import annotations

import socket
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from distkeras_tpu.parallel import mesh as mesh_lib


def determine_host_address() -> str:
    """Reference-parity helper: this host's routable IP address."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))  # no packets sent; just picks an interface
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> None:
    """Join the jax coordination service (multi-host entry point).

    Call this FIRST, before anything touches the jax backend. With no
    arguments it self-detects: on a TPU pod / launcher-managed job (cluster
    env vars present) it joins the coordination service with inferred
    arguments; on a plain single host it is a no-op — so driver scripts are
    portable between one chip and a pod, the analogue of the reference
    working the same in Spark local[N] and cluster mode.
    """
    explicit = any(a is not None for a in
                   (coordinator_address, num_processes, process_id))
    if not explicit and not _cluster_env_present():
        return  # plain single host — nothing to coordinate
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)


def _cluster_env_present() -> bool:
    """True when a supported launcher's environment is visible (the cases
    jax.distributed.initialize can self-infer from)."""
    import os

    markers = (
        "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",      # multi-slice TPU
        "CLOUD_TPU_TASK_ID",
        "OMPI_MCA_orte_hnp_uri",              # OpenMPI
    )
    if any(os.environ.get(m) for m in markers):
        return True
    # pod metadata lists >1 worker (a single-host TPU VM also carries this
    # var — sometimes empty — so require an actual multi-host list)
    if "," in os.environ.get("TPU_WORKER_HOSTNAMES", ""):
        return True
    if int(os.environ.get("SLURM_JOB_NUM_NODES", "1") or 1) > 1:
        return True
    return False


def multihost_mesh(num_workers: Optional[int] = None,
                   model_parallelism: int = 1) -> Mesh:
    """Build the (workers, model) mesh over ALL processes' devices.

    The model axis is laid out over adjacent devices (same host/slice: ICI);
    the workers axis spans hosts (DCN-tolerant all-reduce). With
    ``jax.process_count() == 1`` this degrades to ``mesh.make_mesh``.
    """
    devices = jax.devices()  # global across processes
    if num_workers is None:
        num_workers = len(devices) // model_parallelism
    need = num_workers * model_parallelism
    if need > len(devices):
        raise ValueError(
            f"Mesh needs {need} devices, {len(devices)} visible globally")
    grid = np.asarray(devices[:need]).reshape(num_workers, model_parallelism)
    return Mesh(grid, (mesh_lib.WORKER_AXIS, mesh_lib.MODEL_AXIS))


def process_info() -> dict:
    """Topology snapshot for logging/debugging (the reference printed the
    PS host/port; we print the coordination-service view)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": [str(d) for d in jax.local_devices()],
        "global_device_count": len(jax.devices()),
        "host_address": determine_host_address(),
    }
