"""Host-driven TRUE-async mode: wall-clock asynchrony against a live center.

The substrate (parallel/substrate.py) *emulates* asynchrony deterministically
inside one compiled program — the fast path. This module is the other half of
the reference's story: like dist-keras's socket parameter server
(``parameter_servers.py``/``workers.py`` — unverified, mount empty), workers
here run CONCURRENTLY (host threads standing in for Spark executors), each
looping pull → local window → commit against a ParameterServer whose center
updates live between any two of a worker's steps. Staleness is real thread
scheduling, not a rotation schedule.

TPU mapping: each worker's window is ONE jitted scan (compiled once, shared
by all workers), and each worker thread is PINNED to a device
(``devices[k % D]``) — its carry and staged batches live there, it pulls
the center across the interconnect, computes its window locally, and
commits back to the center's device (the PS folds on device 0). With one
device, threads serialize at window granularity — the interleaving the
reference's executors had against the driver's lock; with D devices,
windows overlap in real wall-clock, which is the multi-chip extension of
the same semantics. Either way the center lives in HBM instead of driver
RAM and the pull/commit hops are explicit device-to-device copies instead
of pickled TCP.
"""

from __future__ import annotations

import contextlib
import queue as queue_lib
import threading
import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu import comms, engine, observability, telemetry
from distkeras_tpu import precision as precision_lib
from distkeras_tpu.data.prefetch import prefetch
from distkeras_tpu.health import recorder as flight_recorder
from distkeras_tpu.health.heartbeat import (HeartbeatPublisher,
                                            StragglerDetector)
from distkeras_tpu.utils import fault
from distkeras_tpu.utils.fetch import device_get_batched
from distkeras_tpu.parameter_servers import (
    DeltaParameterServer,
    DynSGDParameterServer,
    ParameterServer,
)
from distkeras_tpu.parallel.remote_ps import PSUnavailable
from distkeras_tpu.parallel.strategies import Strategy


def _tree_add(a, b):
    """Leafwise sum — the degradation ladder's backlog accumulator."""
    return jax.tree.map(lambda x, y: x + y, a, b)


@contextlib.contextmanager
def _window_trace(enabled: bool, wid: int, fold: int):
    """Root one trace per worker window (DESIGN.md §15): the trace.window
    span parents the pull/compute/commit spans below it, and the commit's
    traceparent rides the wire so transport retries and shard folds in
    OTHER processes chain under this same trace_id."""
    if not enabled or telemetry.get_registry() is None:
        yield None
        return
    ctx = telemetry.TraceContext.new_root(worker=str(wid), window=str(fold))
    with telemetry.use_trace(ctx):
        with telemetry.span("trace.window", worker=wid) as child:
            yield child


def server_for(strategy: Strategy, params) -> ParameterServer:
    """The reference's trainer→server pairing (SURVEY.md §2)."""
    if strategy.name == "dynsgd":
        return DynSGDParameterServer(params)
    return DeltaParameterServer(params)


class CadenceTrigger:
    """Checkpoint cadence on a GLOBALLY counted clock (ADVICE r5 fix).

    ``clock_at_fold`` counts commits from EVERY process, but each process
    observes it only at its own commits — with P processes a local commit
    lands on an exact multiple of ``checkpoint_folds`` only ~1/P of the
    time, so the old ``(clock+1) % folds == 0`` trigger diluted the cadence
    by ~P. Firing on cadence-interval CROSSING instead — did the observed
    clock enter a later ``folds``-sized bucket than the last trigger —
    preserves the knob's meaning (≈ one snapshot per ``folds`` commits) for
    any observation stride. Thread-safe: concurrent workers observing the
    same crossing fire exactly once.
    """

    def __init__(self, folds: int, start_clock: int = 0):
        if folds < 1:
            raise ValueError(f"checkpoint_folds must be >= 1, got {folds}")
        self.folds = int(folds)
        # commits [0, start_clock) predate this run (resume): their
        # intervals must not retrigger
        self._bucket = int(start_clock) // self.folds
        self._lock = threading.Lock()

    def crossed(self, clock_at_fold: int) -> bool:
        bucket = (int(clock_at_fold) + 1) // self.folds
        if bucket <= self._bucket:  # unlocked fast path: no crossing
            return False
        with self._lock:
            if bucket <= self._bucket:
                return False  # a sibling claimed this crossing first
            self._bucket = bucket
            return True


def make_window_fn(model, loss, tx, strategy: Strategy, window: int,
                   metric_names: Sequence[str], seed: int,
                   accum_steps: int = 1, precision: Optional[str] = None):
    """One worker's compiled round: λ local steps + commit computation.

    (carry, center, batches, fold_key) -> (carry, commit, metrics dict)
    where batches leaves are [window, batch, ...]. Compiled once; every
    worker thread calls the same executable.

    ``accum_steps > 1`` microbatches each of the λ local steps
    (engine.make_accum_grad_fn). Accumulation lives entirely inside the
    local step's grad fn, so a window is still λ optimizer steps and ONE
    commit — server clock, commit counts, and staleness histograms are
    unchanged by construction.

    ``precision`` threads a PrecisionPolicy into the grad fns. Strategies
    call the grad fn without a live ``loss_scale``, so the STATIC policy
    scale applies on this path (NUMERICS.md "Low-precision step
    equivalence") — the dynamic-scale plumbing is a sync-path feature.
    """
    accum_steps = int(accum_steps)
    if accum_steps > 1:
        grad_fn = engine.make_accum_grad_fn(model, loss, accum_steps,
                                            metric_names, precision=precision)
    else:
        grad_fn = engine.make_grad_fn(model, loss, precision=precision)
    base_key = jax.random.key(seed)

    def window_fn(carry, center, batches, fold_key):
        carry = strategy.round_start(carry, center)

        def one_step(c, xs):
            batch, i = xs
            rng = jax.random.fold_in(jax.random.fold_in(base_key, fold_key), i)
            c, m = strategy.local_step(grad_fn, tx, c, batch,
                                       rngs={"dropout": rng})
            out = {"loss": m["loss"]}
            for name in metric_names:
                if accum_steps > 1:
                    out[name] = engine.finalize_metric(m["logits"][name])
                else:
                    out[name] = engine.compute_metric(name, m["logits"],
                                                      batch["labels"])
            return c, out

        idx = jnp.arange(window, dtype=jnp.int32)
        carry, ms = jax.lax.scan(one_step, carry, (batches, idx))
        commit = strategy.commit(carry, center, window)
        if not strategy.resets_to_center:
            # local side of the elastic update (EASGD family); the DOWNPOUR
            # family re-pulls the live center at its next round_start instead
            carry = strategy.post_commit(carry, commit, None)
        return carry, commit, ms

    return jax.jit(window_fn)


class HostAsyncRunner:
    """Run N concurrent workers against a live parameter server.

    ``shards``: per-worker lists of staged batch dicts (features/labels),
    each leaf [window, batch, ...]. Each window's metrics are tagged with
    the server clock at its commit; the returned history/staleness are the
    windows sorted by that clock — true commit order, not worker-major
    concatenation.
    """

    def __init__(self, model, loss, tx, strategy: Strategy, window: int,
                 metrics: Sequence[str] = (), seed: int = 0,
                 devices: Optional[Sequence[jax.Device]] = None,
                 codec: Optional[str] = None, overlap: bool = False,
                 accum_steps: int = 1, precision: Optional[str] = None,
                 max_degraded_windows: int = 16, trace: bool = True):
        self.strategy = strategy
        self.window = int(window)
        # distributed tracing (DESIGN.md §15): each worker window becomes
        # one trace whose spans follow the commit through the transport
        # (retries, reconnects) and across shard folds. trace=False keeps
        # the plain (context-free) span events — the tracing-off baseline
        # benchmarks/attribution.py measures overhead against.
        self.trace = bool(trace)
        # merged multi-process rows from the last run_cross_process (set
        # on process 0 when the coordinator mounts a collector)
        self.fleet_telemetry: Optional[list] = None
        # degradation ladder budget (DESIGN.md §13): how many consecutive
        # compute-only windows a worker rides out against an unreachable
        # fleet (stale center, commits accumulated locally) before the
        # outage is surfaced as the underlying PSUnavailable
        self.max_degraded_windows = int(max_degraded_windows)
        self.accum_steps = int(accum_steps)
        self.window_fn = make_window_fn(model, loss, tx, strategy, window,
                                        tuple(metrics), seed,
                                        accum_steps=self.accum_steps,
                                        precision=precision)
        self.tx = tx
        # worker k runs on devices[k % D]; default = single-device mode
        self.devices = list(devices) if devices else [jax.devices()[0]]
        # wire codec for the PS exchange. With a runner-created (local) PS
        # a non-raw codec wraps it in EncodedParameterServer so commits and
        # pulls see exactly the wire numerics; with an injected ps= the
        # caller owns the codec (run_cross_process negotiates it per
        # connection).
        self.codec = None if codec is None \
            else comms.get_codec(codec)
        # overlap=True double-buffers each worker: the previous window's
        # commit and the next window's pull run on a per-worker comms
        # thread while the current window computes (see _overlapped_rounds)
        self.overlap = bool(overlap)
        # health plane (DESIGN.md §9), default-on like the rest of the
        # telemetry: every worker window publishes a heartbeat and feeds
        # the straggler detector; the watchdog stays opt-in (run(...,
        # watchdog=...)) because its policies can abort training
        self.heartbeat = HeartbeatPublisher()
        self.straggler = StragglerDetector()
        # live per-window MFU series (DESIGN.md §21 satellite): bookkeep
        # publishes observability.mfu every window so the mfu-floor SLO
        # burns on current data, not a stale end-of-run gauge. The window
        # FLOPs count is one make_jaxpr trace, taken lazily on the first
        # window and ONLY once a peak ceiling is known — on CPU hosts
        # device_peak_flops is None and the whole path stays cold.
        policy = precision_lib.get_policy(precision)
        self.mfu_dtype = policy.mfu_dtype if policy is not None else "bf16"
        self.mfu_peak_flops: Optional[float] = None  # bench/test override
        self._mfu_peak: Optional[float] = None
        self._mfu_peak_resolved = False
        self._window_flops: Optional[float] = None
        self._mfu_lock = threading.Lock()
        self.worker_devices: list = []  # actual placement, for tests/logs
        self.window_clocks: list = []   # merged commit clocks, last run
        self.merged_windows: list = []  # (clock, staleness, steps) tuples

    def run(self, init_params, epoch_shards: Sequence[Sequence[Sequence[dict]]],
            checkpointer=None, checkpoint_folds: int = 0,
            start_clock: int = 0, ps=None, worker_offset: int = 0,
            fetch_final: bool = True, watchdog=None,
            snapshot_extra=None) -> tuple:
        """``epoch_shards[epoch][worker]`` is that worker's list of staged
        rounds for that epoch (per-epoch staging preserves the sync path's
        reshuffle-every-epoch semantics; pass the same object per epoch when
        not shuffling). Workers progress through epochs without barriers —
        true asynchrony extends across epoch boundaries too. A worker entry
        may also be a ZERO-ARG CALLABLE returning its round iterable — the
        streaming data service (data/service.py) passes lease-driven
        generators this way, so rounds materialize lazily on the worker's
        own prefetch thread instead of being staged up front.

        ``checkpointer``/``checkpoint_folds``: snapshot the live center +
        server clock every ``checkpoint_folds`` commits (the async-mode
        fault-tolerance story — there is no epoch barrier to snapshot at).
        A dedicated saver thread does the pull + device→host fetch + (async
        Orbax) save; committing workers only set an event, so they never
        stall on checkpoint IO (an in-commit-path save would skew the real
        scheduling this mode exists to measure). The PS lock makes each
        pulled snapshot internally consistent. ``start_clock`` seeds the
        server clock when resuming from such a snapshot.

        ``ps``: inject a live parameter server instead of creating one —
        the cross-process mode (parallel/remote_ps.py) passes process 0's
        service-fronted PS here on process 0 and a RemoteParameterServer
        client elsewhere; the worker loop cannot tell the difference.
        ``worker_offset``: this process's first GLOBAL worker id (keeps
        dropout fold keys distinct across processes).

        ``watchdog``: optional :class:`~distkeras_tpu.health.watchdog.
        TrainingWatchdog`. Every worker window feeds it its (fault-hook
        filtered) mean loss and a progress tick; a trip under an aborting
        policy stops every worker at its next round. The runner binds the
        watchdog's crash-time ``checkpoint_fn`` (live-center snapshot via
        ``checkpointer``) and its ``on_trip`` abort hook when unset.

        ``snapshot_extra``: optional zero-arg callable returning a dict of
        extra leaves merged into every checkpoint snapshot (periodic saver
        AND crash-time). The streaming data plane passes
        ``lambda: {"data_cursor": coordinator.cursor_carry()}`` so the
        shuffle cursor rides the same save the center does (DESIGN.md
        §20); keys must not collide with ``center``/``clock``."""
        num_workers = len(epoch_shards[0])
        if ps is None:
            # center (and its folds) live on device 0; workers pull across
            ps = server_for(self.strategy,
                            jax.device_put(init_params, self.devices[0]))
            ps.num_updates = int(start_clock)
            if self.codec is not None and self.codec.name != "raw":
                # single-process codec run: every pull/commit crosses the
                # codec exactly as it would on the wire
                ps = comms.EncodedParameterServer(ps, self.codec)
        # snapshots and the final fetch read the center EXACTLY — a lossy
        # wire codec must not round the saved/returned params, only the
        # worker exchange
        base_ps = getattr(ps, "ps", ps) \
            if isinstance(ps, comms.EncodedParameterServer) else ps
        # per-window records: (commit_clock, staleness, [per-step metrics])
        windows: list[list[tuple]] = [[] for _ in range(num_workers)]
        errors: list = []
        self.worker_devices = [self.devices[k % len(self.devices)]
                               for k in range(num_workers)]
        save_trigger = threading.Event()
        stop_saving = threading.Event()

        def saver():
            """Best-effort periodic snapshots, serialized in one thread.
            Cadence crossings that arrive while a save is in flight coalesce
            into the next snapshot (which sees a newer clock anyway)."""
            last_saved = int(start_clock)
            try:
                while True:
                    fired = save_trigger.wait(timeout=0.05)
                    if fired:
                        save_trigger.clear()
                    elif stop_saving.is_set():
                        return
                    else:
                        continue
                    # consistent under the PS lock
                    center, clock = base_ps.pull()
                    if clock > last_saved:
                        t0 = time.perf_counter()
                        snap = {"center": device_get_batched(center),
                                "clock": np.array([clock], np.int64)}
                        if snapshot_extra is not None:
                            snap.update(snapshot_extra())
                        checkpointer.save(clock, snap)
                        # the stall an in-commit-path save WOULD have cost
                        # a worker (pull + fetch + save dispatch) — the
                        # number that justifies the dedicated saver thread
                        telemetry.histogram("host_async.save_s").record(
                            time.perf_counter() - t0)
                        telemetry.counter("host_async.save.count").inc()
                        last_saved = clock
            except Exception as e:  # surface save failures to the caller
                errors.append(e)

        abort = threading.Event()

        def worker(k: int):
            try:
                dev = self.worker_devices[k]
                wid = worker_offset + k  # GLOBAL worker id (telemetry label)
                pull_h = telemetry.histogram("host_async.pull_s", worker=wid)
                win_h = telemetry.histogram("host_async.window_s", worker=wid)
                commit_h = telemetry.histogram("host_async.commit_s",
                                               worker=wid)
                lag_h = telemetry.histogram("host_async.commit_clock_lag",
                                            worker=wid)
                carry = jax.device_put(
                    self.strategy.init_carry(init_params, self.tx), dev)

                def staged_rounds():
                    # device placement runs on the prefetch thread one
                    # round ahead, so H2D staging overlaps the previous
                    # window's compute
                    for shards in epoch_shards:
                        rounds = shards[k]
                        if callable(rounds):  # lease-driven stream source
                            rounds = rounds()
                        for batches in rounds:
                            yield jax.device_put(batches, dev)

                def bookkeep(clock_at_fold: int, pull_clock: int, ms,
                             win_s: float):
                    # commits the center absorbed between this worker's
                    # pull and its own fold — real scheduling staleness
                    staleness = clock_at_fold - pull_clock
                    lag_h.record(staleness)
                    ms = device_get_batched(ms)
                    n = len(ms["loss"])
                    windows[k].append((
                        clock_at_fold, staleness,
                        [{key: float(v[i]) for key, v in ms.items()}
                         for i in range(n)]))
                    # live health plane: heartbeat + straggler verdict are
                    # published BEFORE the watchdog gets to raise, so the
                    # introspection endpoints see the window that tripped
                    self.heartbeat.publish(wid, clock_at_fold, staleness,
                                           win_s)
                    self.straggler.observe(wid, win_s)
                    self._publish_window_mfu(win_s)
                    if checkpointing and cadence.crossed(clock_at_fold):
                        save_trigger.set()  # non-blocking hand-off
                    if watchdog is not None:
                        watchdog.observe_loss(fault.apply(
                            "host_async.window_loss",
                            float(np.mean(ms["loss"]))))
                        watchdog.notify_progress()

                elastic = getattr(ps, "elastic", False)
                if elastic:
                    try:
                        # join the fleet (lease on the coordinator shard);
                        # best-effort — a commit is also an implicit join
                        ps.register(wid)
                    except Exception:
                        pass
                if self.overlap:
                    self._overlapped_rounds(
                        k, wid, dev, carry, ps, staged_rounds(), abort,
                        bookkeep, pull_h, win_h, commit_h)
                else:
                    self._serial_rounds(
                        k, wid, dev, carry, ps, elastic, staged_rounds(),
                        abort, bookkeep, pull_h, win_h, commit_h)
                if elastic:
                    try:
                        # clean leave — a crashed worker never gets here,
                        # and the lease sweep evicts it instead
                        ps.deregister(wid)
                    except Exception:
                        pass
            except Exception as e:  # surface thread failures to the caller
                if e not in errors:  # a watchdog on_trip may have filed it
                    errors.append(e)
                # forensics: the failing worker's last windows are on the
                # flight-recorder ring; preserve them before the run dies
                telemetry.record_event(
                    "worker_error", worker=worker_offset + k,
                    error=type(e).__name__, message=str(e)[:200])
                flight_recorder.auto_dump(
                    "ps_unavailable" if isinstance(e, PSUnavailable)
                    else "worker_exception")
                abort.set()  # fail fast: siblings stop at their next round
                             # (the reference analogue: Spark killing the
                             # job when a task fails terminally)

        checkpointing = checkpointer is not None and checkpoint_folds > 0
        cadence = (CadenceTrigger(checkpoint_folds, start_clock)
                   if checkpointing else None)
        if watchdog is not None:
            if watchdog.checkpoint_fn is None and checkpointer is not None:
                def crash_checkpoint():
                    # live-center snapshot at trip time (the consistent
                    # read the saver thread also relies on); wait() so the
                    # files exist before the trip aborts the process
                    center, clock = base_ps.pull()
                    snap = {"center": device_get_batched(center),
                            "clock": np.array([clock], np.int64)}
                    if snapshot_extra is not None:
                        snap.update(snapshot_extra())
                    checkpointer.save(clock, snap)
                    checkpointer.wait()
                watchdog.checkpoint_fn = crash_checkpoint
            if watchdog.on_trip is None:
                def on_trip(err):
                    # files the error itself (the stall monitor thread has
                    # no caller to raise into) and stops every worker
                    if err not in errors:
                        errors.append(err)
                    abort.set()
                watchdog.on_trip = on_trip
            watchdog.start_stall_monitor()
        saver_thread = None
        if checkpointing:
            saver_thread = threading.Thread(target=saver, daemon=True)
            saver_thread.start()
        threads = [threading.Thread(target=worker, args=(k,), daemon=True)
                   for k in range(num_workers)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            if watchdog is not None:
                watchdog.stop_stall_monitor()
            if saver_thread is not None:
                stop_saving.set()
                saver_thread.join()
        if errors:
            raise errors[0]
        # merge worker windows by the server clock at their commit — the
        # wall-clock order the center actually absorbed them in
        merged = sorted((w for ws in windows for w in ws), key=lambda w: w[0])
        self.window_clocks = [w[0] for w in merged]  # for tests/diagnostics
        self.merged_windows = merged  # cross-process history upload source
        history = [step for _, _, steps in merged for step in steps]
        stal = [float(s) for _, s, _ in merged]
        if not fetch_final:
            # cross-process caller takes center/clock from the history
            # barrier instead; skipping here saves a redundant full-params
            # transfer (+ a clock roundtrip) per remote process
            return None, history, stal, -1
        center, _ = base_ps.pull()
        return device_get_batched(center), history, stal, ps.num_updates

    def _mfu_ceiling(self) -> Optional[float]:
        """Peak FLOP/s the per-window MFU series measures against: the
        explicit ``mfu_peak_flops`` override (bench/test seam) or the
        device's dtype-aware table entry; None (CPU) disables the series
        — declining beats fabricating, same rule as ``calibrate_peak``."""
        if self.mfu_peak_flops is not None:
            return self.mfu_peak_flops
        if not self._mfu_peak_resolved:
            self._mfu_peak_resolved = True
            try:
                self._mfu_peak = observability.device_peak_flops(
                    self.devices[0], dtype=self.mfu_dtype)
            except Exception:
                self._mfu_peak = None
        return self._mfu_peak

    def _note_window_flops(self, *args) -> None:
        """Count one window's model FLOPs (a single make_jaxpr trace) the
        first time a worker reaches its window; skipped entirely while no
        peak ceiling is known, so the default CPU path never pays it."""
        if self._window_flops is not None or self._mfu_ceiling() is None:
            return
        with self._mfu_lock:
            if self._window_flops is None:
                try:
                    self._window_flops = observability.count_flops(
                        self.window_fn, *args)
                except Exception:
                    self._window_flops = 0.0  # can't count: stay silent

    def _publish_window_mfu(self, win_s: float) -> None:
        if not self._window_flops or win_s <= 0:
            return
        peak = self._mfu_ceiling()
        if peak is None:
            return
        value = observability.mfu(self._window_flops, win_s,
                                  peak_per_chip=peak,
                                  dtype=self.mfu_dtype)
        if value is not None:
            # the gauge (inside mfu()) carries "now"; the histogram keeps
            # the whole window series for burn-rate math and summaries
            telemetry.histogram("observability.mfu_window",
                                dtype=self.mfu_dtype).record(value)

    def _serial_rounds(self, k, wid, dev, carry, ps, elastic, rounds,
                       abort, bookkeep, pull_h, win_h, commit_h):
        """The serialized pull → window → commit loop, with the elastic
        degradation ladder (DESIGN.md §13): when the fleet is unreachable
        (typed PSUnavailable after the transport's own retries), the
        worker degrades to compute-only windows — it keeps training
        against its last good center and accumulates the unfolded commits
        locally — then folds the combined backlog in one commit when the
        fleet returns. ``last_update`` of that fold is the OLDEST backlog
        window's pull clock, so the server charges the honest staleness
        (and DynSGD down-weights accordingly). Bookkeeping for backlog
        windows is deferred until their fold clock exists. Bounded by
        ``max_degraded_windows``; the final backlog (if the run ends
        degraded) gets one last flush attempt before the error surfaces.
        """
        fold = 0
        degraded = 0        # consecutive windows without a landed commit
        backlog = None      # accumulated unfolded commit deltas
        backlog_clock = 0   # pull clock of the OLDEST unfolded window
        deferred: list = []  # (pull_clock, ms, win_s) awaiting a fold clock
        last_center = None  # last successfully pulled (center, clock)
        # step-time decomposition (DESIGN.md §15): the top-level phases
        # data_wait/pull/h2d/compute/commit/bookkeep PARTITION each window
        # (attribution.py asserts they sum to >=95% of window wall-time);
        # encode/decode/fold land as nested sub-phases from the codec/PS
        prof = {name: telemetry.histogram(f"profile.phase.{name}_s",
                                          worker=wid)
                for name in ("data_wait", "pull", "h2d", "compute",
                             "commit", "bookkeep", "window")}
        it = iter(prefetch(rounds, depth=1))
        while True:
            t_start = time.perf_counter()
            try:
                batches = next(it)
            except StopIteration:
                break
            if abort.is_set():
                return  # a sibling died: stop wasting windows
            # per-window phase breakdown, mirrored onto the flight-recorder
            # ring as ONE structured event per window — the postmortem
            # bundle's "trailing windows" evidence (histograms only keep
            # aggregates; the ring keeps the last windows individually)
            phases = {"data_wait": time.perf_counter() - t_start}
            prof["data_wait"].record(phases["data_wait"])
            with _window_trace(self.trace, wid, fold):
                t0 = time.perf_counter()
                try:
                    with telemetry.span("trace.pull", worker=wid):
                        center, clock = ps.pull()
                    last_center = (center, clock)
                except PSUnavailable:
                    if last_center is None:
                        raise  # never reached the fleet at all: real error
                    center, clock = last_center  # compute-only: stale
                t1 = time.perf_counter()
                pull_h.record(t1 - t0)
                prof["pull"].record(t1 - t0)
                phases["pull"] = t1 - t0
                center_dev = jax.device_put(center, dev)
                t_h2d = time.perf_counter()
                prof["h2d"].record(t_h2d - t1)
                phases["h2d"] = t_h2d - t1
                self._note_window_flops(carry, center_dev, batches,
                                        np.int32(wid * 1_000_003 + fold))
                with telemetry.span("trace.compute", worker=wid):
                    carry, commit, ms = self.window_fn(
                        carry, center_dev, batches,
                        np.int32(wid * 1_000_003 + fold))
                    jax.block_until_ready(commit)
                t2 = time.perf_counter()
                win_s = t2 - t1  # h2d + compute, as before the split
                win_h.record(win_s)
                prof["compute"].record(t2 - t_h2d)
                phases["compute"] = t2 - t_h2d
                to_send, last_up = commit, clock
                if backlog is not None:
                    to_send = _tree_add(backlog, commit)
                    last_up = backlog_clock
                landed = True
                try:
                    with telemetry.span("trace.commit", worker=wid):
                        if elastic:
                            clock_at_fold = ps.commit(
                                to_send, last_update=last_up,
                                worker=wid, window_s=win_s)
                        else:
                            clock_at_fold = ps.commit(to_send,
                                                      last_update=last_up)
                except PSUnavailable as e:
                    degraded += 1
                    telemetry.counter("host_async.degraded_windows",
                                      worker=wid).inc()
                    telemetry.record_event("degraded_window", worker=wid,
                                           window=fold, degraded=degraded)
                    if degraded > self.max_degraded_windows:
                        # ladder exhausted: this outage is terminal — put
                        # the judgement next to the evidence before the
                        # raise unwinds the worker
                        telemetry.record_event(
                            "ps_unavailable", worker=wid,
                            degraded=degraded, message=str(e)[:200])
                        flight_recorder.auto_dump("ps_unavailable")
                        raise
                    backlog, backlog_clock = to_send, last_up
                    deferred.append((clock, ms, win_s))
                    landed = False
                if landed:
                    t3 = time.perf_counter()
                    commit_h.record(t3 - t2)
                    prof["commit"].record(t3 - t2)
                    phases["commit"] = t3 - t2
                    degraded = 0
                    backlog = None
                    for d_clock, d_ms, d_win_s in deferred:
                        bookkeep(clock_at_fold, d_clock, d_ms, d_win_s)
                    deferred.clear()
                    bookkeep(clock_at_fold, clock, ms, win_s)
                    phases["bookkeep"] = time.perf_counter() - t3
                    prof["bookkeep"].record(phases["bookkeep"])
            phases["window"] = time.perf_counter() - t_start
            prof["window"].record(phases["window"])
            telemetry.record_event(
                "window_profile", worker=wid, window=fold,
                degraded=degraded > 0,
                phases={k: round(v, 6) for k, v in phases.items()})
            fold += 1
        if backlog is not None:
            # the run ended degraded: one last flush so the backlogged
            # windows are not silently dropped from the center/history
            clock_at_fold = ps.commit(backlog, last_update=backlog_clock)
            for d_clock, d_ms, d_win_s in deferred:
                bookkeep(clock_at_fold, d_clock, d_ms, d_win_s)

    def _overlapped_rounds(self, k, wid, dev, carry, ps, rounds, abort,
                           bookkeep, pull_h, win_h, commit_h):
        """Double-buffered worker loop: while window n computes, a
        per-worker comms thread commits window n-1 and pulls the center
        for window n+1. Hides commit+pull latency behind compute — the
        win that matters when the PS is remote (remote_ps.py) or the
        codec makes encode/decode non-trivial.

        Semantics: the center a window consumes is one window OLDER with
        respect to the worker's OWN commits than in the serialized loop
        (center for window n+1 is pulled before commit n folds). Clocks
        stay exact — staleness is measured from the actual pull/commit
        clock pair, so the histogram reflects the extra self-staleness
        rather than hiding it; CadenceTrigger still fires on true fold
        clocks (one window later in this worker's observation stride).

        Elastic note: this path gets the transport's reconnect/retry and
        stamps worker identity (lease renewal), but NOT the compute-only
        degradation ladder — the double-buffered hand-off has no place to
        park a backlog without stalling the compute loop it exists to
        keep busy. An outage longer than the retry budget surfaces as
        PSUnavailable; use the serialized loop for churn-heavy fleets.
        """
        elastic = getattr(ps, "elastic", False)
        _STOP = object()
        req: queue_lib.Queue = queue_lib.Queue(maxsize=1)
        resp: queue_lib.Queue = queue_lib.Queue(maxsize=1)

        def comms_loop():
            # one request in flight at a time: commit the finished window
            # (if any), then pull the next center. Exceptions travel to
            # the compute loop through the resp queue.
            try:
                while True:
                    item = req.get()
                    if item is _STOP:
                        return
                    commit, pull_clock = item
                    clock_at_fold = -1
                    if commit is not None:
                        t0 = time.perf_counter()
                        if elastic:
                            clock_at_fold = ps.commit(
                                commit, last_update=pull_clock, worker=wid)
                        else:
                            clock_at_fold = ps.commit(commit,
                                                      last_update=pull_clock)
                        dt = time.perf_counter() - t0
                        commit_h.record(dt)
                        # overlapped comms still feed the phase profile;
                        # attribution reads them as hidden-behind-compute
                        telemetry.histogram("profile.phase.commit_s",
                                            worker=wid).record(dt)
                    t0 = time.perf_counter()
                    center, clock = ps.pull()
                    dt = time.perf_counter() - t0
                    pull_h.record(dt)
                    telemetry.histogram("profile.phase.pull_s",
                                        worker=wid).record(dt)
                    resp.put((center, clock, clock_at_fold))
            except Exception as e:
                resp.put(e)

        ct = threading.Thread(target=comms_loop, daemon=True,
                              name=f"host-async-comms-{wid}")
        ct.start()
        try:
            req.put((None, 0))  # prime: pull window 0's center
            fold = 0
            pending = None  # (pull_clock, ms, win_s) awaiting its fold clock
            for batches in prefetch(rounds, depth=1):
                if abort.is_set():
                    return  # a sibling died: stop wasting windows
                got = resp.get()
                if isinstance(got, Exception):
                    raise got
                center, clock, clock_at_fold = got
                if pending is not None:
                    # the previous window's commit has now folded; its
                    # clock arrived with this response
                    bookkeep(clock_at_fold, *pending)
                t1 = time.perf_counter()
                center_dev = jax.device_put(center, dev)
                self._note_window_flops(carry, center_dev, batches,
                                        np.int32(wid * 1_000_003 + fold))
                carry, commit, ms = self.window_fn(
                    carry, center_dev, batches,
                    np.int32(wid * 1_000_003 + fold))
                jax.block_until_ready(commit)
                win_s = time.perf_counter() - t1
                win_h.record(win_s)
                pending = (clock, ms, win_s)
                req.put((commit, clock))
                fold += 1
            if pending is not None:
                got = resp.get()  # drain the final window's commit
                if isinstance(got, Exception):
                    raise got
                bookkeep(got[2], *pending)
        finally:
            req.put(_STOP)
            ct.join()


def run_cross_process(runner: HostAsyncRunner, init_params, epoch_shards,
                      *, worker_offset: int, checkpointer=None,
                      checkpoint_folds: int = 0, start_clock: int = 0,
                      service_port: int = 0,
                      history_timeout: float = 600.0,
                      watchdog=None, ps_shards: int = 1,
                      ps_placement: str = "process0",
                      ps_standby: bool = False,
                      snapshot_extra=None) -> tuple:
    """Pod-scale TRUE-async: this process's worker threads against ONE live
    center owned by process 0 (VERDICT r4 ask #2 — the reference's
    workers-on-separate-machines semantics).

    Process 0 hosts the device-resident PS behind a
    :class:`~distkeras_tpu.parallel.remote_ps.ParameterServerService`; its
    own workers hit the PS object directly (no loopback tax), every other
    process's workers pull/commit through a RemoteParameterServer client.
    Staleness is real cross-host interleaving on the server clock.

    End of run: every process uploads its commit-clock-tagged windows;
    ``history_get`` doubles as the completion barrier (it blocks until all
    processes uploaded) and returns the clock-merged global history plus
    the final center — so every process returns IDENTICAL
    ``(params, history, staleness, num_updates)``, matching the sync
    path's process-transparency. Checkpointing runs only on process 0
    (it owns the center; snapshot cadence is evaluated at its workers'
    commit clocks, which carry the global count).

    ``ps_shards > 1`` replaces the single service with an elastic fleet
    (parallel/elastic.py): process 0 hosts N shard services (the center's
    leaves size-balanced across them, shard 0 carrying the membership/
    lease/history plane), the address broadcast carries the whole shard
    map, and EVERY process's workers — including process 0's, which give
    up the no-loopback-tax direct path — go through a
    ShardedRemoteParameterServer, so the whole fleet is on the membership
    plane and churn handling is uniform.

    ``ps_placement="spread"`` (DESIGN.md §17) deals the shard services
    round-robin over PROCESSES instead of stacking them all on process 0:
    the token travels first (everyone must authenticate their service
    before any address exists), each process binds its assigned shards,
    and the full address map is all-gathered — so the fleet aggregates
    every host's NIC and survives a non-coordinator host loss outright.
    Degenerates to "process0" at one process.

    ``ps_standby=True`` adds the coordinator-failover plane: a dark
    standby service (on shard 1's process under spread placement — a
    different HOST than the coordinator) receives the coordinator's
    write-behind authority log, and every client gets the standby's
    address so a dead coordinator is re-resolved through the reconnect
    path instead of ending the run (parallel/failover.py).
    """
    from jax.experimental import multihost_utils

    from distkeras_tpu.health.collector import TelemetryCollector
    from distkeras_tpu.parallel import elastic as elastic_mod
    from distkeras_tpu.parallel import remote_ps as rps

    ps_shards = int(ps_shards)
    if ps_shards < 1:
        raise ValueError(f"ps_shards must be >= 1, got {ps_shards}")
    nproc = jax.process_count()
    placement = elastic_mod.shard_placement(ps_shards, nproc, ps_placement)
    spread = any(p != 0 for p in placement)
    pid = jax.process_index()
    codec_name = "raw" if runner.codec is None else runner.codec.name
    service = client = None
    services: list = []

    def _make_ps(part):
        ps = server_for(
            runner.strategy,
            jax.device_put(part, runner.devices[0]))
        ps.num_updates = int(start_clock)
        return ps

    try:
        if spread:
            # multi-host placement: the token travels FIRST (every hosting
            # process must authenticate its services before any address
            # exists), each process binds its assigned shards dark, and the
            # complete address map is all-gathered before the fleet is
            # cross-wired and started
            if pid == 0:
                import secrets

                _, token = rps.share_service_address(
                    [], token=secrets.token_hex(16))
            else:
                _, token = rps.share_service_address(None)
            # the authoritative start state (checkpoint-restored on process
            # 0) must seed EVERY hosting process's shards, not just 0's
            init_params = multihost_utils.broadcast_one_to_all(
                jax.tree.map(np.asarray, device_get_batched(init_params)))
            from distkeras_tpu.parallel.distributed import \
                determine_host_address
            mine = [s for s in range(ps_shards) if placement[s] == pid]
            standby_here = ps_standby and \
                pid == elastic_mod.standby_process(placement)
            services = elastic_mod.make_ps_fleet(
                _make_ps, init_params, ps_shards,
                expected_processes=nproc, token=token,
                straggler=(StragglerDetector()
                           if 0 in mine or standby_here else None),
                advertise_host=determine_host_address(),
                local_shards=mine, standby=standby_here)
            for svc in services:
                # the fleet telemetry sink lives on the coordinator shard,
                # next to membership and history
                if svc.shard == 0 and not svc.is_standby:
                    svc.collector = TelemetryCollector()
            addresses, standby_addr = elastic_mod.gather_fleet_addresses(
                services, ps_shards)
            elastic_mod.connect_fleet(
                services, addresses, standby_address=standby_addr,
                token=token)
            client = elastic_mod.ShardedRemoteParameterServer(
                addresses, init_params, timeout=history_timeout + 60.0,
                token=token, codec=codec_name, standby=standby_addr)
            local_ps = client
        elif pid == 0:
            # symmetric go/no-go (ADVICE r5): if service construction fails
            # here, peers must RAISE at the address broadcast instead of
            # blocking in it until the collective timeout
            try:
                import secrets

                token = secrets.token_hex(16)
                if ps_shards == 1 and not ps_standby:
                    ps = _make_ps(init_params)
                    service = rps.ParameterServerService(
                        ps, init_params,
                        expected_processes=nproc,
                        port=service_port, token=token,
                        collector=TelemetryCollector())
                    service.start()
                    ports: Any = service.port
                else:
                    # a fresh detector: the services see worker-stamped
                    # window durations from every process, the runner's
                    # own detector only this process's — mixing the two
                    # feeds would double-count local workers
                    advertise = "127.0.0.1"
                    if nproc > 1:
                        from distkeras_tpu.parallel.distributed import \
                            determine_host_address
                        advertise = determine_host_address()
                    services = elastic_mod.make_ps_fleet(
                        _make_ps, init_params, ps_shards,
                        expected_processes=nproc,
                        token=token, straggler=StragglerDetector(),
                        advertise_host=advertise, standby=ps_standby)
                    # the fleet telemetry sink lives on the coordinator
                    # shard, next to membership and history
                    services[0].collector = TelemetryCollector()
                    ports = [svc.advertised for svc in services
                             if not svc.is_standby]
                    for svc in services:
                        # standby rides the same broadcast, "~"-marked so
                        # clients wire it as failover target, not a shard
                        if svc.is_standby:
                            ports.append("~" + svc.advertised)
            except Exception:
                rps.share_service_address(None, error=True)
                raise
            addr, _ = rps.share_service_address(ports, token=token)
            if ps_shards == 1 and not ps_standby:
                local_ps = ps
                if runner.codec is not None and runner.codec.name != "raw":
                    # process 0's workers skip the socket but must see the
                    # SAME wire numerics as remote peers, or convergence
                    # depends on which process a worker landed on
                    local_ps = comms.EncodedParameterServer(ps, runner.codec)
            else:
                # loopback sharded client: process 0's workers join the
                # same membership plane as everyone else's
                client = elastic_mod.ShardedRemoteParameterServer(
                    [svc.advertised for svc in services
                     if not svc.is_standby], init_params,
                    timeout=history_timeout + 60.0, token=token,
                    codec=codec_name,
                    standby=next((svc.advertised for svc in services
                                  if svc.is_standby), None))
                local_ps = client
        else:
            addr, token = rps.share_service_address(None)
            entries = addr.split(",")
            standby_addr = next(
                (e[1:] for e in entries if e.startswith("~")), None)
            addresses = [e for e in entries if not e.startswith("~")]
            # socket timeout must outlive the history barrier, or a slow
            # pod turns the server's informative barrier-timeout error
            # into a bare client-side socket.timeout
            if len(addresses) == 1 and standby_addr is None:
                client = rps.RemoteParameterServer(
                    addresses[0], init_params,
                    timeout=history_timeout + 60.0, token=token,
                    codec=codec_name)
            else:
                client = elastic_mod.ShardedRemoteParameterServer(
                    addresses, init_params, timeout=history_timeout + 60.0,
                    token=token, codec=codec_name, standby=standby_addr)
            local_ps = client
            # the authoritative start state lives at the center (matters on
            # resume: process 0 restored it; also seeds EASGD replicas)
            init_params, _ = client.pull()
        runner.run(init_params, epoch_shards,
                   checkpointer=checkpointer if pid == 0 else None,
                   checkpoint_folds=checkpoint_folds if pid == 0 else 0,
                   start_clock=start_clock, ps=local_ps,
                   worker_offset=worker_offset, fetch_final=False,
                   watchdog=watchdog,
                   snapshot_extra=snapshot_extra if pid == 0 else None)
        if pid == 0 and client is None:
            service.put_history(0, runner.merged_windows)
            merged, center, clock = service.get_history_blocking(
                timeout=history_timeout)
        else:
            client.put_history(pid, runner.merged_windows)
            merged, center, clock = client.get_history(
                timeout=history_timeout)
        # fleet telemetry aggregation: every process that does not HOST
        # the coordinator pushes its registry rows to the coordinator's
        # collector (best-effort) after the history barrier, so the push
        # rides an idle, settled fleet
        reg = telemetry.get_registry()
        hosts_coord = service is not None or any(
            svc.shard == 0 and not svc.is_standby for svc in services)
        if reg is not None and client is not None and (
                pid != 0 or not hosts_coord):
            client.put_telemetry(pid, list(reg.rows()))
        # everyone holds the final state before process 0 tears the
        # service down (a late reader must not hit a dead socket); the
        # barrier also orders the pushes above before the merge below
        multihost_utils.sync_global_devices("distkeras_host_async_done")
        if pid == 0:
            # the collector follows the coordinator: after a failover the
            # promoted standby's re-mounted collector (seeded from the
            # replicated mirror) holds the fleet rows, not the dead
            # coordinator's
            collector = service.collector if service is not None else None
            promoted = [svc for svc in services
                        if svc.standby is not None and svc.standby.promoted]
            if promoted:
                collector = promoted[-1].collector
            elif collector is None:
                for svc in services:
                    if svc.shard == 0 and not svc.is_standby:
                        collector = svc.collector
            if collector is not None:
                runner.fleet_telemetry = collector.merged_rows(local_pid=0)
            elif client is not None:
                # spread fleet whose coordinator lives on another host
                runner.fleet_telemetry = client.get_merged_telemetry()
    finally:
        if client is not None:
            client.close()
        if service is not None:
            service.stop()
        for svc in services:
            if svc.replicator is not None:
                svc.replicator.close(timeout=1.0)
            svc.stop()
    history = [step for _, _, steps in merged for step in steps]
    stal = [float(s) for _, s, _ in merged]
    return device_get_batched(center), history, stal, int(clock)


def stage_worker_shards(shards, features_col: str, label_col: str,
                        batch_size: int, window: int) -> list:
    """Host-side staging for the async runner: per-worker lists of
    [window, batch, ...] batch dicts (rounds of λ minibatches)."""
    out = []
    per_round = batch_size * window
    for s in shards:
        rounds = len(s) // per_round
        rs = []
        for r in range(rounds):
            lo = r * per_round
            feats = np.asarray(s[features_col][lo:lo + per_round])
            labs = np.asarray(s[label_col][lo:lo + per_round])
            rs.append({
                "features": feats.reshape((window, batch_size) +
                                          feats.shape[1:]),
                "labels": labs.reshape((window, batch_size) +
                                       labs.shape[1:]),
            })
        out.append(rs)
    return out


def stream_worker_rounds(address: str, worker: int, features_col: str,
                         label_col: str, batch_size: int, window: int,
                         token: Optional[str] = None, dataset=None,
                         max_ranges: int = 2):
    """A lease-driven round source for one worker: returns the ZERO-ARG
    CALLABLE :meth:`HostAsyncRunner.run` accepts as an ``epoch_shards``
    worker entry (streaming admission, DESIGN.md §20).

    Each call opens a fresh :class:`~distkeras_tpu.data.service.
    DataServiceClient` (the client is not thread-safe; one per worker
    thread) and drives lease → materialize → ack against the coordinator
    at ``address``, reshaping leased row ranges into the exact
    ``[window, batch, ...]`` round dicts :func:`stage_worker_shards`
    produces — the worker loop cannot tell staged and streamed rounds
    apart. Rows come from ``dataset`` locally when given, else over the
    wire. Epoch advancement is coordinator-side; the generator ends when
    the coordinator reports the stream exhausted.

    Accounting honesty: a range is acked once the consumer advances past
    it, which can precede the emission of the round holding its final
    rows — rows buffered toward an incomplete round when a worker dies
    are bounded by ``batch_size * window + max_ranges * range_size``, the
    same drop-remainder class of loss :func:`stage_worker_shards` has at
    every shard tail."""
    def rounds():
        from distkeras_tpu.data.service import (DataServiceClient,
                                                stream_ranges)
        per_round = batch_size * window
        client = DataServiceClient(address, worker=worker, token=token)
        client.register()
        cols = [features_col, label_col]
        feats = labs = None  # row backlog pending reshape into rounds
        try:
            for _e, _pos, _start, _stop, rows in stream_ranges(
                    client, dataset=dataset, cols=cols,
                    max_ranges=max_ranges):
                f, l = np.asarray(rows[features_col]), \
                    np.asarray(rows[label_col])
                feats = f if feats is None else np.concatenate([feats, f])
                labs = l if labs is None else np.concatenate([labs, l])
                while len(feats) >= per_round:
                    tf, feats = feats[:per_round], feats[per_round:]
                    tl, labs = labs[:per_round], labs[per_round:]
                    yield {
                        "features": tf.reshape((window, batch_size) +
                                               tf.shape[1:]),
                        "labels": tl.reshape((window, batch_size) +
                                             tl.shape[1:]),
                    }
        finally:
            try:
                client.deregister()
            except Exception:
                pass
            client.close()
    return rounds
