"""The parallel substrate: strategies lifted onto a device mesh.

This module is the load-bearing design swap of the whole framework
(SURVEY.md §5 "Distributed communication backend"): where the reference runs
a socket parameter server on the driver and workers commit/pull pickled
deltas over TCP (``distkeras/networking.py``/``parameter_servers.py`` —
unverified, mount empty), here the center variable is device-resident
replicated state and every round's commits are folded with ONE staleness-
weighted ``psum`` over the ``workers`` mesh axis, inside a single jitted
computation. An epoch is `lax.scan(rounds) ∘ lax.scan(window)` — no Python in
the hot loop, no host round-trips, collectives ride ICI.

Asynchrony is emulated deterministically: each worker's commit is assigned a
schedule position per round (rotating by default), and staleness-aware
strategies (DynSGD) weight commits by that position. See NUMERICS.md and
DESIGN.md for why determinism-by-construction replaces TCP-timing accidents.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu import engine
from distkeras_tpu.parallel import mesh as mesh_lib
from distkeras_tpu.parallel.strategies import Carry, Strategy
from distkeras_tpu.utils.jax_compat import shard_map
from distkeras_tpu.utils.trees import tree_add, tree_scale

WORKERS = mesh_lib.WORKER_AXIS


def build_epoch_fn(model, loss, tx: optax.GradientTransformation,
                   strategy: Strategy, mesh: Mesh, num_workers: int,
                   window: int, metrics: Sequence[str] = (),
                   dropout_seed: int = 0, accum_steps: int = 1,
                   precision=None,
                   bucket_bytes: Optional[int] = None) -> Callable:
    """Compile the per-epoch distributed training function.

    ``num_workers`` is the LOGICAL worker count K; when it exceeds the mesh's
    ``workers`` axis size D, each device runs K/D stacked replicas (the
    reference's ``parallelism_factor`` oversubscription: more partitions than
    executors). Logical worker k lives on device k // (K/D); the staleness
    rotation and the center fold run over all K, so K workers on D devices
    compute the same training trajectory as K workers on K devices.

    ``accum_steps > 1`` turns each local step into a scan over that many
    microbatches (engine.make_accum_grad_fn); the per-step batch is split on
    its leading axis, so peak activation memory shrinks by ~accum_steps while
    λ/window accounting is untouched — a window is still ``window`` optimizer
    steps and one commit, and DynSGD staleness weights see the same schedule.

    Returns ``epoch_fn(center, carries, data, round_offset) ->
    (center, carries, metrics)`` where

    - ``center``: replicated params pytree (the parameter server state),
    - ``carries``: per-worker Carry pytree with leading ``num_workers`` axis,
    - ``data``: dict of arrays shaped (rounds, num_workers, window, batch,
      ...) — round-major, the layout ``lax.scan`` consumes directly (see
      :func:`mesh.round_major_sharded`),
    - ``round_offset``: int32 scalar, global round counter (continues the
      staleness rotation across epochs),
    - ``metrics``: dict of (num_workers, rounds, window) float arrays plus
      per-round ``staleness`` (num_workers, rounds).

    ``precision=`` threads a mixed-precision policy into the grad fn
    (static loss scale — strategies call grad fns with three args, so the
    live guard scale does not reach this path; DESIGN.md §11).

    ``bucket_bytes=`` partitions the commit fold's all-reduce into
    size-targeted buckets issued per-bucket (collectives.bucketed_psum) so
    XLA's async collectives overlap the fold with the surrounding compute;
    the per-leaf sums are identical, so the trajectory is bitwise-equal to
    the unbucketed fold (tests/test_overlap.py).
    """
    from distkeras_tpu.parallel import collectives

    metric_names = tuple(metrics)
    accum_steps = int(accum_steps)
    if accum_steps > 1:
        # terms-accumulating grad fn: same (params, batch, rngs) contract,
        # but aux is {metric: (num, den)} instead of logits — strategies
        # pass it through opaquely, the step body finalizes below
        grad_fn = engine.make_accum_grad_fn(model, loss, accum_steps,
                                            metric_names,
                                            precision=precision)
    else:
        grad_fn = engine.make_grad_fn(model, loss, precision=precision)
    base_key = jax.random.key(dropout_seed)
    mesh_workers = mesh.shape[WORKERS]
    if num_workers % mesh_workers != 0:
        raise ValueError(
            f"num_workers={num_workers} must be a multiple of the mesh's "
            f"workers axis ({mesh_workers}); pick parallelism_factor so "
            f"logical workers divide evenly onto devices")
    factor = num_workers // mesh_workers

    def worker_epoch(center, carry, data, round_offset):
        # Per-device data block: (rounds, factor, window, batch, ...) —
        # round-major staging means lax.scan consumes axis 0 directly, no
        # device-side transpose of the whole chunk. `factor` is this
        # device's count of stacked logical workers (1 without
        # oversubscription).
        d = jax.lax.axis_index(WORKERS)
        ks = d * factor + jnp.arange(factor, dtype=jnp.int32)
        num_rounds = jax.tree.leaves(data)[0].shape[0]

        def run_worker(k, carry, batches, center, r_idx):
            """One logical worker's round: pull, window of steps, commit.
            ``center``/``r_idx`` are broadcast (vmap in_axes=None)."""
            carry = strategy.round_start(carry, center)

            def one_step(c, step_xs):
                batch, i = step_xs
                rng = jax.random.fold_in(
                    jax.random.fold_in(jax.random.fold_in(base_key, k),
                                       r_idx), i)
                c, m = strategy.local_step(grad_fn, tx, c, batch,
                                           rngs={"dropout": rng})
                out = {"loss": m["loss"]}
                for name in metric_names:
                    if accum_steps > 1:
                        out[name] = engine.finalize_metric(m["logits"][name])
                    else:
                        out[name] = engine.compute_metric(
                            name, m["logits"], batch["labels"])
                return c, out

            step_idx = jnp.arange(window, dtype=jnp.int32)
            carry, step_ms = jax.lax.scan(one_step, carry, (batches, step_idx))
            if not strategy.exchanges:
                step_ms["staleness"] = jnp.float32(0.0)
                return carry, step_ms, ()
            commit = strategy.commit(carry, center, window)
            position = (k + r_idx) % num_workers
            weighted = tree_scale(commit, strategy.staleness_weight(position))
            step_ms["staleness"] = position.astype(jnp.float32)
            return carry, step_ms, (weighted, commit)

        def one_round(state, xs):
            center, carry = state
            r_idx, batches = xs
            carry, step_ms, ex = jax.vmap(
                run_worker, in_axes=(0, 0, 0, None, None))(
                    ks, carry, batches, center, r_idx)
            if strategy.exchanges:
                weighted, commits = ex
                # fold: sum this device's replicas, then psum across
                # devices — bucketed when bucket_bytes is set so the
                # all-reduce overlaps compute (bitwise-equal either way)
                local = jax.tree.map(lambda x: jnp.sum(x, axis=0), weighted)
                new_center = tree_add(center, collectives.bucketed_psum(
                    local, WORKERS, bucket_bytes))
                carry = jax.vmap(
                    lambda c, cm: strategy.post_commit(c, cm, new_center)
                )(carry, commits)
            else:
                new_center = center
            return (new_center, carry), step_ms

        rounds = round_offset + jnp.arange(num_rounds, dtype=jnp.int32)
        (center, carry), ms = jax.lax.scan(one_round, (center, carry),
                                           (rounds, data))
        # metrics go back workers-leading for the sharded out_specs (tiny
        # arrays — this transpose is noise, unlike one on the data would be)
        ms = jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), ms)
        return center, carry, ms

    shmapped = shard_map(
        worker_epoch, mesh=mesh,
        in_specs=(P(), P(WORKERS), P(None, WORKERS), P()),
        out_specs=(P(), P(WORKERS), P(WORKERS)),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0, 1))


def init_center_and_carries(params, tx, strategy: Strategy, mesh: Mesh,
                            num_workers: int) -> Tuple[Any, Any]:
    """Place the center (replicated) and per-worker carries (sharded).

    All replicas start from the center — the reference's model broadcast.
    """
    center = mesh_lib.put_replicated(params, mesh)
    carry = strategy.init_carry(params, tx)
    stacked = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_workers,) + jnp.shape(x)),
        carry)
    carries = mesh_lib.put_worker_sharded(stacked, mesh)
    return center, carries


def stage_epoch_data(shards, features_col: str, label_col: str,
                     batch_size: int, window: int, mesh: Mesh,
                     max_rounds: Optional[int] = None):
    """Host-side data staging: per-worker shards -> one sharded device array
    shaped (rounds, workers, window, batch, ...).

    Every worker gets the same round count (static shapes — XLA's contract);
    the common count is the smallest shard's, surplus rows are dropped (the
    reference's analogue: Spark partitions simply finish at different times).

    This is the whole-epoch-resident path (fine for benchmark-sized data);
    for datasets that don't fit as one device buffer use
    :func:`stage_epoch_chunks`.
    """
    return next(stage_epoch_chunks(shards, features_col, label_col,
                                   batch_size, window, mesh,
                                   max_rounds=max_rounds))


def stage_epoch_chunks(shards, features_col: str, label_col: str,
                       batch_size: int, window: int, mesh: Mesh,
                       chunk_rounds: Optional[int] = None,
                       max_rounds: Optional[int] = None,
                       local_positions: Optional[Sequence[int]] = None):
    """Return a generator of ``(device_data, rounds)`` chunks of at most
    ``chunk_rounds`` rounds each, keeping staging memory O(chunk) instead
    of O(epoch).

    ``jax.device_put`` is asynchronous, so a caller that dispatches the
    (also asynchronous) epoch computation on chunk *i* and only then pulls
    chunk *i+1* from this generator gets host slicing + host->device
    transfer overlapped with device compute — double buffering without any
    explicit machinery. The final chunk may be ragged (one extra XLA
    compilation, amortized across epochs).

    Two multi-process data contracts:

    - ``local_positions=None`` (default, replicated): every process holds
      the SAME full dataset; ``shards`` covers all logical workers and
      ``put_global`` carves each process's addressable part.
    - ``local_positions=[w0, w1, ...]`` (host-sharded): the process stages
      shards ONLY for its own mesh worker-axis positions (see
      ``mesh.local_worker_positions``); ``shards`` holds each position's
      logical workers contiguously, factor per position — this process
      never materializes (or even holds) other hosts' rows. The common
      round count is negotiated across processes (a tiny allgather, once
      per call — eager, not inside the generator, so it runs on the
      caller's thread in program order on every host).
    """
    per_round = batch_size * window
    local_rounds = min(len(s) // per_round for s in shards)
    rounds = local_rounds
    if local_positions is not None and jax.process_count() > 1:
        # global min: shard sizes may differ across hosts
        from jax.experimental import multihost_utils

        rounds = int(np.min(multihost_utils.process_allgather(
            np.int64(rounds))))
    if max_rounds is not None:
        rounds = min(rounds, max_rounds)
    if rounds == 0:
        if rounds != local_rounds:
            raise ValueError(
                f"A PEER process's shards cannot form a single round of "
                f"window={window} x batch={batch_size} (negotiated global "
                f"round count is 0; this host's shards of sizes "
                f"{[len(s) for s in shards]} could form {local_rounds})")
        raise ValueError(
            f"Shards of sizes {[len(s) for s in shards]} cannot form a "
            f"single round of window={window} x batch={batch_size}")
    if chunk_rounds is None:
        chunk_rounds = rounds
    cols = {"features": features_col, "labels": label_col}
    # columns are kept lazy here (ndarray views, memmaps, ShardedColumns);
    # np.asarray happens per chunk slice below, so file-backed shards are
    # read from disk in O(chunk) pieces, never materialized whole
    arrs = {key: [s[col] for s in shards] for key, col in cols.items()}
    sharding = mesh_lib.round_major_sharded(mesh)
    mesh_workers = mesh.shape[WORKERS]

    def gen():
        for start in range(0, rounds, chunk_rounds):
            cnt = min(chunk_rounds, rounds - start)
            lo = start * per_round
            hi = lo + cnt * per_round

            def stack(key):
                # round-major: (rounds, workers, window, batch, ...)
                return np.stack([
                    np.asarray(a[lo:hi]).reshape(
                        (cnt, window, batch_size) + tuple(a.shape[1:]))
                    for a in arrs[key]], axis=1)

            data = {key: stack(key) for key in cols}
            if local_positions is None:
                yield mesh_lib.put_global(data, sharding), cnt
            else:
                yield mesh_lib.put_host_sharded(
                    data, sharding, mesh_workers, local_positions), cnt

    return gen()
