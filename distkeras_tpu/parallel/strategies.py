"""Update-rule algebra for the async trainer zoo — "workers.py" re-derived.

Reference parity: each dist-keras algorithm pairs a Worker loop
(``distkeras/workers.py``) with a parameter-server policy
(``distkeras/parameter_servers.py``) — both unverified (mount empty); the
exact rules implemented here are pinned in NUMERICS.md with their paper
provenance and enforced by golden tests.

Design: a Strategy is a bundle of PURE pytree functions — no sockets, no
threads, no device placement. The parallel substrate lifts them onto a mesh
(shard_map + psum); the golden tests run them sequentially on CPU. This split
is what makes the async algebra unit-testable, which the reference never was
(SURVEY.md §4: it had no tests at all).

Round shape shared by all strategies (λ = communication_window):

    round_start -> λ × local_step -> commit -> [server: c += Σ s_k·commit_k]
    -> post_commit

The center fold is additive, so the substrate can apply it with one psum of
staleness-weighted commits per round.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from distkeras_tpu.utils.trees import tree_add, tree_scale, tree_sub, tree_zeros_like


class Carry(NamedTuple):
    """Per-worker replica state threaded through scans."""
    params: Any
    opt_state: Any
    extra: Any  # strategy-private (e.g. EAMSGD velocity)


class Strategy:
    """Base: DOWNPOUR-family behavior (pull, local tx steps, delta commit)."""

    name = "base"
    #: True when the local replica is reset to the fresh center after a
    #: commit (DOWNPOUR family); EASGD family keeps its replica.
    resets_to_center = True
    #: False for strategies that never exchange (Independent) — lets the
    #: substrate skip the per-round psum + center update entirely.
    exchanges = True

    def init_carry(self, params, tx: optax.GradientTransformation) -> Carry:
        return Carry(params=params, opt_state=tx.init(params), extra=())

    def round_start(self, carry: Carry, center) -> Carry:
        """Pull: DOWNPOUR family starts each round from the center."""
        return carry._replace(params=center)

    def local_step(self, grad_fn, tx, carry: Carry, batch,
                   rngs=None) -> Tuple[Carry, dict]:
        """One minibatch step with the worker optimizer."""
        (loss, logits), grads = grad_fn(carry.params, batch, rngs)
        updates, opt_state = tx.update(grads, carry.opt_state, carry.params)
        params = optax.apply_updates(carry.params, updates)
        return (carry._replace(params=params, opt_state=opt_state),
                {"loss": loss, "logits": logits})

    def commit(self, carry: Carry, center, window: int):
        """What gets sent to the server: accumulated delta."""
        return tree_sub(carry.params, center)

    def staleness_weight(self, position):
        """Server-side scale for a commit applied at schedule position
        ``position`` (0 = first/freshest)."""
        return jnp.asarray(1.0, jnp.float32)

    def post_commit(self, carry: Carry, commit, new_center) -> Carry:
        """After the exchange: DOWNPOUR family pulls the fresh center."""
        if self.resets_to_center:
            return carry._replace(params=new_center)
        return carry


class Downpour(Strategy):
    """DOWNPOUR (Dean et al. 2012): windowed delta push, fresh-center pull."""

    name = "downpour"


class ADAG(Strategy):
    """ADAG: DOWNPOUR with accumulated-gradient normalization — the commit is
    divided by the window so the server step is λ-invariant (NUMERICS.md)."""

    name = "adag"

    def commit(self, carry: Carry, center, window: int):
        return tree_scale(tree_sub(carry.params, center), 1.0 / window)


class DynSGD(Strategy):
    """DynSGD: DOWNPOUR deltas, server scales each by 1/(staleness+1).

    Host-side folds (``parameter_servers.dynsgd_fold_weight``, and the
    elastic late-fold path in ``parallel/remote_ps.py``) must stay in
    lockstep with this device-side rule — it is the same curve traced in
    float32 instead of python floats.
    """

    name = "dynsgd"

    def staleness_weight(self, position):
        return 1.0 / (position.astype(jnp.float32) + 1.0)


class AEASGD(Strategy):
    """Asynchronous EASGD (Zhang et al. 2015): persistent local replicas with
    symmetric elastic attraction E = ρ·η·(w − c)."""

    name = "aeasgd"
    resets_to_center = False

    def __init__(self, rho: float, learning_rate: float):
        self.rho = float(rho)
        self.learning_rate = float(learning_rate)

    def round_start(self, carry: Carry, center) -> Carry:
        return carry  # replica persists; center only read at commit time

    def commit(self, carry: Carry, center, window: int):
        alpha = self.rho * self.learning_rate
        return tree_scale(tree_sub(carry.params, center), alpha)

    def post_commit(self, carry: Carry, commit, new_center) -> Carry:
        # worker side of the elastic update: w ← w − E
        return carry._replace(params=tree_sub(carry.params, commit))


class EAMSGD(AEASGD):
    """EAMSGD: AEASGD with explicit Nesterov momentum on the local replica
    (v ← μv − η∇f(w + μv); w ← w + v). The worker-optimizer kwarg is ignored
    by design — momentum lives in the worker loop, as in the reference."""

    name = "eamsgd"

    def __init__(self, rho: float, learning_rate: float, momentum: float):
        super().__init__(rho, learning_rate)
        self.momentum = float(momentum)

    def init_carry(self, params, tx) -> Carry:
        return Carry(params=params, opt_state=(),
                     extra=tree_zeros_like(params))

    def local_step(self, grad_fn, tx, carry: Carry, batch,
                   rngs=None) -> Tuple[Carry, dict]:
        mu, eta = self.momentum, self.learning_rate
        v = carry.extra
        lookahead = jax.tree.map(lambda w, vi: w + mu * vi, carry.params, v)
        (loss, logits), grads = grad_fn(lookahead, batch, rngs)
        v = jax.tree.map(lambda vi, g: mu * vi - eta * g, v, grads)
        params = tree_add(carry.params, v)
        return (carry._replace(params=params, extra=v),
                {"loss": loss, "logits": logits})


class Independent(Strategy):
    """No exchange at all: replicas train in isolation (AveragingTrainer /
    EnsembleTrainer substrate). Commits are zero so the center never moves;
    the trainer reads the per-worker replicas at the end (mean for
    Averaging, all of them for Ensemble)."""

    name = "independent"
    resets_to_center = False
    exchanges = False

    def round_start(self, carry: Carry, center) -> Carry:
        return carry

    def commit(self, carry: Carry, center, window: int):
        return tree_zeros_like(carry.params)


def get(name: str, *, learning_rate: float = 0.01, **kwargs) -> Strategy:
    """Resolve a strategy by trainer name. Rejects hyperparameters the
    selected strategy doesn't take — a misdirected rho/momentum should fail
    loudly, not be silently dropped."""
    name = name.lower()

    def _done():
        if kwargs:
            raise TypeError(
                f"Strategy {name!r} does not take {sorted(kwargs)}")

    if name == "downpour":
        _done()
        return Downpour()
    if name == "adag":
        _done()
        return ADAG()
    if name == "dynsgd":
        _done()
        return DynSGD()
    if name == "aeasgd":
        rho = kwargs.pop("rho", 5.0)
        _done()
        return AEASGD(rho, learning_rate)
    if name == "eamsgd":
        rho = kwargs.pop("rho", 5.0)
        momentum = kwargs.pop("momentum", 0.9)
        _done()
        return EAMSGD(rho, learning_rate, momentum)
    if name == "independent":
        _done()
        return Independent()
    raise ValueError(f"Unknown strategy {name!r}")
