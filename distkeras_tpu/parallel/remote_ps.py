"""Cross-process TRUE-async: a live parameter service over the pod fabric.

Reference parity: dist-keras's defining deployment is workers on SEPARATE
machines training against a live parameter server on the driver
(``distkeras/parameter_servers.py``/``networking.py`` — unverified, mount
empty): a socket server, per-connection handler threads, and pickled
center/delta dicts on the wire. This module is that topology rebuilt for a
TPU pod (VERDICT r4 ask #2):

- process 0's **device-resident** ParameterServer (parameter_servers.py —
  center in HBM, jitted folds) is fronted by :class:`ParameterServerService`,
  a socket server with the reference's accept-loop/handler-thread shape;
- every process's HostAsyncRunner worker threads pull/commit through
  :class:`RemoteParameterServer`, a drop-in for the ParameterServer
  interface (process 0's workers talk to the object directly — no loopback
  tax on the host that owns the center);
- the wire is length-prefixed JSON headers + raw array bytes — **no
  pickle**: nothing on the wire can execute code, and leaves decode
  zero-copy into numpy. It rides whatever IP fabric connects the hosts
  (DCN on a pod, loopback in the two-process tests).

Staleness here is REAL: commits from different hosts interleave at the
center in wall-clock order, and each commit's staleness is the server
clock distance since that worker's pull — across processes, not just
across threads.

End-of-run bookkeeping rides the same wire: each process uploads its
(commit-clock-tagged) window records; ``history_get`` blocks until every
process has uploaded, then returns the clock-merged history plus the
final center — so all processes finish with identical history and params,
matching the sync path's process-transparency.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np

from distkeras_tpu import comms, telemetry
from distkeras_tpu.health import recorder as flight_recorder
from distkeras_tpu.health.endpoints import HEALTH_OPS, handle_health_op
from distkeras_tpu.health.membership import Membership
from distkeras_tpu.parameter_servers import ParameterServer, \
    dynsgd_fold_weight
from distkeras_tpu.utils import fault
from distkeras_tpu.utils.fetch import device_get_batched


# -- wire format -----------------------------------------------------------
# [u32 header_len][header JSON (utf-8)][blob 0][blob 1]...
# header["blob_lens"] carries the byte length of each trailing blob.
# Public: the serving front-end (distkeras_tpu/serving/server.py) speaks
# the same framing and the same token scheme.
#
# Blob CONTENT is codec-dependent (comms/codec.py): a connection starts on
# the raw codec and may switch after a {"op": "hello", "codec": ...}
# handshake — the server grants the request when it supports that codec and
# answers with the accepted name (fallback: "raw"), after which both ends
# encode/decode every pull/commit blob through it.

def send_message(sock: socket.socket, header: dict,
                 blobs: Sequence = ()):
    """Frame and send. Blobs may be bytes or memoryviews; large ones go out
    as bounded chunks straight from their backing arrays (no whole-message
    join — the old ``b"".join`` copied every leaf a second time)."""
    header = dict(header)
    header["blob_lens"] = [len(b) for b in blobs]
    hb = json.dumps(header).encode()
    sock.sendall(struct.pack("<I", len(hb)) + hb)
    comms.send_buffers(sock, blobs)


def _recvexact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return bytes(buf)


def recv_message(sock: socket.socket) -> Tuple[dict, list]:
    (hlen,) = struct.unpack("<I", _recvexact(sock, 4))
    header = json.loads(_recvexact(sock, hlen))
    blobs = [_recvexact(sock, n) for n in header.get("blob_lens", [])]
    return header, blobs


_sendall = send_message  # internal aliases, kept for brevity below
_recv = recv_message


class PSUnavailable(RuntimeError):
    """The parameter service could not be reached within the retry budget.

    Raised by :class:`RemoteParameterServer` after reconnect/backoff
    exhaustion — the typed signal HostAsyncRunner's degradation ladder
    keys on (compute-only windows against the stale center, fold the
    accumulated delta on reconnect) instead of crashing the worker on a
    bare socket error."""


class HistoryBarrierTimeout(RuntimeError, TimeoutError):
    """The end-of-run history barrier expired before every process (or
    shard) reported — typed so callers can distinguish "the fleet never
    converged on a final center" from a transport timeout, instead of
    silently proceeding with partial history. Also a RuntimeError: that
    is what this condition surfaced as before it was typed, and callers'
    broad handlers keep working."""


class CoordinatorFenced(RuntimeError):
    """The peer is a DEPOSED coordinator: a newer epoch holds the lease
    (DESIGN.md §17). Carries the promoted coordinator's address and the
    fencing epoch, so the sharded client re-resolves without a discovery
    round-trip. A RuntimeError because that is what service error
    replies raised before fencing was typed."""

    def __init__(self, msg: str, coordinator: Optional[str] = None,
                 epoch: int = 0):
        super().__init__(msg)
        self.coordinator = coordinator
        self.epoch = int(epoch)


#: Ops only the CURRENT coordinator may serve: a fenced (deposed)
#: coordinator refuses these with a redirect, and a dark standby refuses
#: them until promoted. Discovery (shard_map/coordinator), replication,
#: promotion, and the health plane stay served in both states.
COORD_OPS = ("pull", "commit", "register", "lease_renew", "deregister",
             "clock", "version", "history_put", "history_get",
             "telemetry_put", "telemetry_merged")


def check_token(expected: Optional[str], header: dict) -> bool:
    """Constant-time shared-token check (ADVICE r5): the service refuses
    any request whose header token does not match the process-0-generated
    secret. ``expected=None`` disables authentication (single-host dev)."""
    if expected is None:
        return True
    import hmac

    got = header.get("token")
    return isinstance(got, str) and hmac.compare_digest(got, expected)


class _TreeCodec:
    """Flatten/unflatten a fixed pytree structure to wire leaf blobs.

    Both ends construct the codec from their own (identically-initialized)
    params tree, so the wire carries only leaf blobs — structure, shapes
    and dtypes are agreed out of band and VERIFIED on decode. The per-leaf
    encoding is delegated to a pluggable wire codec (comms/codec.py,
    default raw); lossy codecs get a worker-side error-feedback accumulator
    so commit quantization error re-enters the next delta instead of being
    lost.
    """

    def __init__(self, like, wire="raw"):
        host = jax.tree.map(np.asarray, device_get_batched(like))
        leaves, self.treedef = jax.tree_util.tree_flatten(host)
        self.specs = [(l.shape, l.dtype) for l in leaves]
        self._raw_bytes = sum(
            int(np.prod(s)) * np.dtype(d).itemsize for s, d in self.specs)
        self.set_wire(wire)

    def set_wire(self, wire) -> None:
        self.wire = comms.get_codec(wire)
        self._ef = comms.ErrorFeedback(self.wire) if self.wire.lossy \
            else None

    def with_wire(self, wire) -> "_TreeCodec":
        """A sibling sharing the (immutable) specs/treedef with its own
        wire codec + error-feedback state — per-connection codecs on the
        server without re-flattening ``like`` per accept."""
        clone = object.__new__(_TreeCodec)
        clone.treedef = self.treedef
        clone.specs = self.specs
        clone._raw_bytes = self._raw_bytes
        clone.set_wire(wire)
        return clone

    def encode(self, tree, kind: str = "commit") -> list:
        t0 = time.perf_counter()
        leaves = [np.asarray(l) for l in jax.tree_util.tree_flatten(
            device_get_batched(tree))[0]]
        if len(leaves) != len(self.specs):
            raise ValueError(
                f"tree has {len(leaves)} leaves, codec expects "
                f"{len(self.specs)}")
        if self._ef is not None and kind == "commit":
            blobs = self._ef.encode_leaves(leaves, self.specs)
        else:
            blobs = [self.wire.encode(l, kind=kind) for l in leaves]
        wire_bytes = sum(len(b) for b in blobs)
        if wire_bytes:
            telemetry.histogram("comms.compress_ratio", op=kind,
                                codec=self.wire.name).record(
                self._raw_bytes / wire_bytes)
        telemetry.histogram("profile.phase.encode_s", op=kind).record(
            time.perf_counter() - t0)
        return blobs

    def decode(self, blobs: Sequence[bytes], kind: str = "commit"):
        t0 = time.perf_counter()
        if len(blobs) != len(self.specs):
            raise ValueError(
                f"message has {len(blobs)} blobs, codec expects "
                f"{len(self.specs)}")
        leaves = [self.wire.decode(b, shape, dtype, kind=kind)
                  for b, (shape, dtype) in zip(blobs, self.specs)]
        tree = jax.tree_util.tree_unflatten(self.treedef, leaves)
        telemetry.histogram("profile.phase.decode_s", op=kind).record(
            time.perf_counter() - t0)
        return tree


class ParameterServerService:
    """Socket front-end for a live ParameterServer (runs on process 0).

    The reference's lifecycle verbs (``start``/``run``/``stop``) and
    thread shape (accept loop + handler thread per connection) are kept;
    the center behind the socket is device-resident and its folds are the
    jitted commits of parameter_servers.py. Also aggregates end-of-run
    window histories from every process (``history_put``/``history_get``).
    """

    #: bounded per-client replay window: how many (seq → reply) entries
    #: the commit dedup cache keeps per cid. A client retries at most one
    #: in-flight commit per worker thread, so 128 is orders of magnitude
    #: of slack — the bound exists so a long run cannot grow the cache.
    DEDUP_CACHE = 128

    def __init__(self, ps: ParameterServer, like,
                 expected_processes: int = 1,
                 host: str = "0.0.0.0", port: int = 0,
                 token: Optional[str] = None,
                 codecs: Optional[Sequence[str]] = None,
                 membership: Optional[Membership] = None,
                 shard: int = 0, num_shards: int = 1,
                 collector=None):
        self.ps = ps
        self.codec = _TreeCodec(like)
        # fleet telemetry sink (health/collector.py): mounted on the
        # coordinator shard only; workers push row batches via the
        # telemetry_put op, readers merge them via telemetry_merged
        self.collector = collector
        # wire codecs this server will grant in the hello handshake
        # (None = everything registered); raw is always granted
        self.supported = tuple(codecs) if codecs is not None \
            else comms.available_codecs()
        self.expected = int(expected_processes)
        self.token = token  # ADVICE r5: required in every request header
        # elastic fleet (DESIGN.md §13): the membership table lives on the
        # coordinator shard (shard 0) only; follower shards fold with the
        # coordinator's explicit weight and keep no member state
        self.membership = membership
        self.shard = int(shard)
        self.num_shards = int(num_shards)
        #: full fleet map ("host:port" per shard), set by the launcher once
        #: every shard is up; served to late joiners via the shard_map op
        self.shard_addresses: Optional[list] = None
        # -- coordinator failover plane (parallel/failover.py) -------------
        #: this service's own reachable address (set by the launcher; the
        #: standby advertises it as the promoted coordinator address)
        self.advertised: Optional[str] = None
        #: the designated standby's address, broadcast to clients so their
        #: reconnect path can re-resolve a dead coordinator
        self.standby_address: Optional[str] = None
        #: a standby service is DARK: coordinator ops refused until its
        #: StandbyState promotes (which flips this back off)
        self.is_standby = False
        #: standby mirror + promotion state machine (StandbyState)
        self.standby = None
        #: the coordinator's write-behind log shipper (Replicator)
        self.replicator = None
        #: a deposed coordinator: a newer epoch fenced it; coordinator ops
        #: are refused with a redirect instead of folding into a stale
        #: center (split-brain guard)
        self.fenced = False
        self.fenced_by: Optional[dict] = None
        #: the promotion epoch this service serves under (0 = the original
        #: coordinator; each handoff increments it)
        self.coord_epoch = 0
        self._dedup: dict = {}  # cid -> OrderedDict(seq -> commit reply)
        self._dedup_lock = threading.Lock()
        self._histories: dict[int, list] = {}
        self._hist_cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._t_start = time.time()
        self._threads: list = []
        self._conns: set = set()  # established connections, for kill()
        self._conn_lock = threading.Lock()

    # -- lifecycle (reference vocabulary) ---------------------------------
    def start(self) -> None:
        self._running = True
        self._t_start = time.time()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            # prune finished handlers (ADVICE r5): the list otherwise grows
            # one entry per connection for the life of the service
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        try:
            # shutdown() wakes an accept() blocked in the loop thread; a
            # bare close() would leave that in-flight syscall holding the
            # open file description, and the kernel would hand it exactly
            # one more connection — which a reconnecting fault-tolerant
            # client is quick enough to be (established connections are
            # deliberately left serving; only the listener dies here)
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def kill(self, reason: str = "chaos") -> None:
        """Simulate PROCESS DEATH for this service (the chaos "kill"
        action): unlike :meth:`stop` — which leaves established
        connections serving — the listener AND every live connection die
        instantly, in-flight requests get no reply, and the flight
        recorder dumps this side's postmortem (carrying the failover
        event) exactly as a crashing coordinator's would."""
        if not self._running:
            return
        telemetry.counter("elastic.failover.kills").inc()
        telemetry.record_event("failover", transition="killed",
                               shard=self.shard, reason=reason,
                               clock=int(self.ps.num_updates))
        if self.replicator is not None:
            self.replicator.close(timeout=0.2)  # the log dies with us
        self.stop()
        with self._conn_lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        flight_recorder.auto_dump(
            "coordinator_killed" if self.shard == 0
            else f"shard{self.shard}_killed")

    def fence(self, epoch: int, coordinator: Optional[str] = None) -> None:
        """Depose this (former) coordinator: a standby promoted under a
        newer epoch. Coordinator ops now refuse with a typed redirect —
        a fenced center must never fold another commit."""
        self.fenced = True
        self.fenced_by = {"epoch": int(epoch),
                          "coordinator": coordinator or self.standby_address}
        telemetry.record_event("failover", transition="deposed",
                               shard=self.shard, epoch=int(epoch))

    # -- per-connection handler (reference: handle_connection) ------------
    def _serve(self, conn: socket.socket):
        inflight = telemetry.gauge("remote_ps.server.inflight_connections")
        inflight.add(1)
        with self._conn_lock:
            self._conns.add(conn)
        codec = self.codec  # per-connection: hello may swap the wire codec
        try:
            with conn:
                while True:
                    try:
                        header, blobs = _recv(conn)
                    except ConnectionError:
                        return
                    if not check_token(self.token, header):
                        telemetry.counter(
                            "remote_ps.server.auth_failures").inc()
                        _sendall(conn, {"error": "authentication failed"})
                        return  # drop the connection, not just the request
                    if header["op"] == "hello":
                        granted = comms.negotiate(
                            header.get("codec", "raw"), self.supported)
                        codec = self.codec.with_wire(granted)
                        telemetry.counter("comms.negotiated",
                                          codec=granted).inc()
                        _sendall(conn, {"codec": granted})
                        continue
                    try:
                        self._dispatch(conn, header, blobs, codec)
                    except ConnectionError:
                        # chaos-injected server reset, or the peer vanished
                        # mid-reply: this connection is done, the service
                        # lives on (the client reconnects and retries)
                        return
        except Exception:
            if self._running:  # surface handler crashes, don't die silently
                raise
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            inflight.add(-1)

    def _dispatch(self, conn, header: dict, blobs: list,
                  codec: Optional[_TreeCodec] = None):
        op = header["op"]
        # the standby replicates shard 0 but is a DIFFERENT process: give
        # it a distinct chaos identity so `shard=0` targets exactly the
        # coordinator (and `shard=-1` exactly the standby)
        act = fault.chaos("remote_ps.server.handle",
                          shard=-1 if self.is_standby else self.shard)
        if act is not None:
            if act.action == "delay":
                time.sleep(act.delay_s)  # a stalled shard, from outside
            elif act.action == "kill":
                # process death, not a connection blip: the whole service
                # (listener + every connection) dies under the caller
                self.kill(reason="chaos")
                raise ConnectionError("chaos: service killed")
            else:  # either reset flavor: kill the connection, no reply
                conn.close()
                raise ConnectionError("chaos: server reset the connection")
        telemetry.counter("remote_ps.server.dispatch", op=op).inc()
        telemetry.counter("remote_ps.server.bytes_received").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("comms.bytes_recv", op=op, side="server").inc(
            sum(len(b) for b in blobs))
        ctx = telemetry.extract(header)
        t0 = time.perf_counter()
        try:
            if ctx is None:
                self._dispatch_op(conn, op, header, blobs,
                                  codec if codec is not None else self.codec)
            else:
                # adopt the caller's trace: server-side handling becomes a
                # child span under the same trace_id, stitched across the
                # socket by the traceparent header
                with telemetry.use_trace(ctx):
                    with telemetry.span("trace.server", op=op,
                                        shard=self.shard):
                        self._dispatch_op(
                            conn, op, header, blobs,
                            codec if codec is not None else self.codec)
        finally:
            telemetry.histogram("remote_ps.server.handle_s",
                                op=op).record(time.perf_counter() - t0)

    @staticmethod
    def _reply(conn, op: str, header: dict, blobs: Sequence = ()):
        telemetry.counter("comms.bytes_sent", op=op, side="server").inc(
            sum(len(b) for b in blobs))
        _sendall(conn, header, blobs)

    def _dispatch_op(self, conn, op: str, header: dict, blobs: list,
                     codec: _TreeCodec):
        if op in COORD_OPS and self.fenced:
            # deposed coordinator: refuse with a redirect to the epoch
            # holder — a fenced center must never fold another commit
            fb = self.fenced_by or {}
            _sendall(conn, {
                "error": "coordinator fenced: epoch "
                         f"{fb.get('epoch', 0)} promoted at "
                         f"{fb.get('coordinator')}",
                "error_kind": "fenced",
                "coordinator": fb.get("coordinator"),
                "epoch": fb.get("epoch", 0)})
            return
        if op in COORD_OPS and self.is_standby:
            _sendall(conn, {"error": "standby shard is dark until "
                                     "promoted", "error_kind": "standby"})
            return
        if op == "pull":
            center, clock = self.ps.pull()
            # model_version rides every pull reply so a rollout
            # controller's poll is one roundtrip (serving/rollout.py)
            self._reply(conn, op,
                        {"clock": clock, "model_version":
                         int(getattr(self.ps, "model_version", 0))},
                        codec.encode(center, kind="pull"))
        elif op == "commit":
            # idempotency check BEFORE decode: a retried commit (client
            # sent, reply lost, client reconnected and re-sent) must fold
            # exactly once, and the replay should not even pay the decode
            cid, seq = header.get("cid"), header.get("seq")
            if cid is not None and seq is not None:
                cached = self._dedup_get(cid, seq)
                if cached is not None:
                    telemetry.counter("remote_ps.server.dedup_hits").inc()
                    telemetry.record_event("wire", outcome="dedup_hit",
                                           cid=cid, seq=seq)
                    self._reply(conn, op, cached)
                    return
            # decode ONCE into the leaves' native dtypes; the PS folds the
            # decoded tree directly (no second materialization)
            delta = codec.decode(blobs, kind="commit")
            worker = header.get("worker")
            weight = header.get("weight")  # follower-shard explicit fold
            if (weight is None and worker is not None
                    and self.membership is not None
                    and self.membership.should_late_fold(worker)):
                # an evicted worker returned: DynSGD-weight its stale
                # commit regardless of server flavor (DESIGN.md §13)
                weight = dynsgd_fold_weight
                telemetry.counter("elastic.late_folds").inc()
            at_fold, applied = self.ps.commit_ex(
                delta, last_update=header["last_update"], weight=weight)
            if worker is not None and self.membership is not None:
                # a landed commit is proof of life: renew the lease,
                # re-admit if evicted, feed the straggler detector
                self.membership.observe_commit(worker,
                                               header.get("window_s"))
            reply = {"at_fold": at_fold, "weight": applied}
            if cid is not None and seq is not None:
                self._dedup_put(cid, seq, reply)
            if self.replicator is not None:
                # write-behind: the fold's verdict + the RAW received
                # blobs ship to the standby asynchronously (zero
                # re-encode, zero added latency on this reply)
                self.replicator.record_commit(
                    blobs=blobs, codec=codec.wire.name, at_fold=at_fold,
                    weight=applied, last_update=header["last_update"],
                    cid=cid, seq=seq)
            self._reply(conn, op, reply)
        elif op == "register":
            if self.membership is None:
                # not the coordinator shard (or membership disabled):
                # lease 0 tells the worker there is no lease to keep
                self._reply(conn, op, {"lease_s": 0.0, "elastic": False})
            else:
                lease = self.membership.register(header["worker"],
                                                 header.get("lease_s"))
                self._reply(conn, op, {"lease_s": lease, "elastic": True})
        elif op == "lease_renew":
            evicted = (self.membership.renew(header["worker"])
                       if self.membership is not None else False)
            self._reply(conn, op, {"evicted": evicted})
        elif op == "deregister":
            if self.membership is not None:
                self.membership.deregister(header["worker"])
            self._reply(conn, op, {"ok": True})
        elif op == "shard_map":
            self._reply(conn, op, {
                "shard": self.shard, "num_shards": self.num_shards,
                "addresses": list(self.shard_addresses or [])})
        elif op == "clock":
            self._reply(conn, op, {"clock": self.ps.pull()[1]})
        elif op == "version":
            # control-plane peek at the published deployment version
            # (serving/rollout.py) without paying a center transfer;
            # ``"set"`` stamps a publish (monotone, refused loudly)
            if header.get("set") is not None:
                try:
                    self.ps.set_model_version(int(header["set"]))
                except (AttributeError, ValueError) as e:
                    _sendall(conn, {"error": str(e)})
                    return
            self._reply(conn, op, {
                "version": int(getattr(self.ps, "model_version", 0)),
                "clock": int(self.ps.num_updates)})
        elif op == "history_put":
            with self._hist_cv:
                self._histories[int(header["pid"])] = header["windows"]
                self._hist_cv.notify_all()
            if self.replicator is not None:
                self.replicator.record_history(int(header["pid"]),
                                               header["windows"])
            self._reply(conn, op, {"ok": True})
        elif op == "history_get":
            # blocks until EVERY process uploaded — the end-of-run barrier.
            # The timeout reply is sent AFTER the cv is released: a socket
            # send under self._hist_cv would freeze every history_put
            # worker behind a slow reader's TCP window for the full I/O
            # wait (dktlint: lock-blocking-call).
            with self._hist_cv:
                self._hist_cv.wait_for(
                    lambda: len(self._histories) >= self.expected,
                    timeout=header.get("timeout", 600))
                uploaded = sorted(self._histories)
                merged = sorted(
                    (w for ws in self._histories.values() for w in ws),
                    key=lambda w: w[0])
            if len(uploaded) < self.expected:
                _sendall(conn, {"error": "history barrier timeout: "
                                f"{uploaded} of "
                                f"{self.expected} processes uploaded",
                                "error_kind": "history-timeout"})
                return
            center, clock = self.ps.pull()
            self._reply(conn, op, {"windows": merged, "clock": clock},
                        codec.encode(center, kind="pull"))
        elif op == "telemetry_put":
            # fleet telemetry aggregation (DESIGN.md §15): a worker pushes
            # its span/metric rows; bounded on the collector side, a
            # best-effort no-op when this shard mounts no collector
            if self.collector is None:
                self._reply(conn, op, {"ok": False, "accepted": 0,
                                       "dropped": 0})
            else:
                res = self.collector.add_batch(header.get("pid", -1),
                                               header.get("rows", []))
                if self.replicator is not None:
                    self.replicator.record_telemetry(
                        header.get("pid", -1), header.get("rows", []))
                self._reply(conn, op, dict(res, ok=True))
        elif op == "telemetry_merged":
            rows = ([] if self.collector is None
                    else self.collector.merged_rows())
            self._reply(conn, op, {"ok": self.collector is not None,
                                   "rows": rows})
        elif op == "repl_append":
            # the coordinator's write-behind log arriving at the standby
            if self.standby is None:
                _sendall(conn, {"error": "not a standby: no replication "
                                         "state mounted"})
            else:
                self._reply(conn, op, self.standby.handle_append(header,
                                                                 blobs))
        elif op == "coord_lease":
            # the coordinator's heartbeat: lease renewal + authority
            # snapshot (clock, membership export)
            if self.standby is None:
                _sendall(conn, {"error": "not a standby: no replication "
                                         "state mounted"})
            else:
                self._reply(conn, op, self.standby.handle_lease(header))
        elif op == "coordinator":
            # discovery: who holds the coordinator lease? On a standby
            # this lazily notices a lapsed lease and promotes (the
            # worker's reconnect path is the failure detector)
            self._reply(conn, op, self.coordinator_view())
        elif op == "promote":
            if self.standby is None:
                _sendall(conn, {"error": "not a standby: nothing to "
                                         "promote"})
            else:
                self._reply(conn, op, self.standby.handle_promote(
                    force=bool(header.get("force", False))))
        elif op in HEALTH_OPS:
            # live health plane (DESIGN.md §9): header-only introspection
            # sharing this connection's framing + token auth
            with self._hist_cv:
                uploaded = len(self._histories)
            self._reply(conn, op, handle_health_op(op, header, extra_status={
                "service": "parameter_server",
                "clock": int(self.ps.num_updates),  # no center fetch
                "model_version": int(getattr(self.ps, "model_version", 0)),
                "expected_processes": self.expected,
                "histories_uploaded": uploaded,
                "uptime_s": round(time.time() - self._t_start, 3),
                "port": self.port,
                "shard": self.shard,
                "num_shards": self.num_shards,
                # failover discovery hints: HealthClient caches these so
                # a later connection loss can follow the coordinator move
                **({"shard_addresses": list(self.shard_addresses)}
                   if self.shard_addresses else {}),
                **({"standby": self.standby_address}
                   if self.standby_address else {}),
                **({"coord_epoch": self.coord_epoch}
                   if self.coord_epoch else {}),
                **({"is_standby": True} if self.is_standby else {}),
                **({"fenced": self.fenced_by} if self.fenced else {}),
                **({"membership": self.membership.status()}
                   if self.membership is not None else {}),
            }))
        else:
            _sendall(conn, {"error": f"unknown op {op!r}"})

    # -- commit idempotency (retried commits fold once) --------------------
    def _dedup_get(self, cid: str, seq) -> Optional[dict]:
        with self._dedup_lock:
            replies = self._dedup.get(cid)
            return None if replies is None else replies.get(int(seq))

    def _dedup_put(self, cid: str, seq, reply: dict) -> None:
        with self._dedup_lock:
            replies = self._dedup.setdefault(cid, collections.OrderedDict())
            replies[int(seq)] = reply
            while len(replies) > self.DEDUP_CACHE:
                replies.popitem(last=False)

    def coordinator_view(self) -> dict:
        """Where this service believes the coordinator lease lives. A
        standby answers from its promotion state machine (and may promote
        while answering); everyone else answers from the fleet map."""
        if self.standby is not None:
            return self.standby.coordinator_view()
        if self.fenced:
            fb = self.fenced_by or {}
            return {"address": fb.get("coordinator"),
                    "epoch": fb.get("epoch", 0), "promoted": True,
                    "standby": self.standby_address}
        return {"address": (self.shard_addresses[0]
                            if self.shard_addresses else self.advertised),
                "epoch": self.coord_epoch, "promoted": self.coord_epoch > 0,
                "standby": self.standby_address}

    # -- direct (in-process) counterparts for process 0 -------------------
    def put_history(self, pid: int, windows: list) -> None:
        windows = [[int(c), float(s), steps] for c, s, steps in windows]
        with self._hist_cv:
            self._histories[int(pid)] = windows
            self._hist_cv.notify_all()
        if self.replicator is not None:
            # process 0's direct upload replicates like the wire one
            self.replicator.record_history(int(pid), windows)

    def get_history_blocking(self, timeout: float = 600):
        with self._hist_cv:
            ok = self._hist_cv.wait_for(
                lambda: len(self._histories) >= self.expected,
                timeout=timeout)
            if not ok:
                raise HistoryBarrierTimeout(
                    f"history barrier: {sorted(self._histories)} of "
                    f"{self.expected} processes uploaded")
            merged = sorted(
                (w for ws in self._histories.values() for w in ws),
                key=lambda w: w[0])
        center, clock = self.ps.pull()
        return merged, device_get_batched(center), clock


class RemoteParameterServer:
    """Client drop-in for the ParameterServer interface over the service.

    One data connection per process; worker threads share it PIPELINED:
    the connection lock covers only the send, and responses are claimed in
    send order by a FIFO of waiters — so a worker's request goes on the
    wire as soon as the previous request finished *sending*, not after its
    full round-trip (the old full-RPC lock made every small request pay
    the largest in-flight commit's RTT, and vice versa). Control-plane ops
    (``num_updates`` polls) ride a separate lazily-opened connection with
    its own server handler thread, so they can never head-of-line-block —
    or be blocked by — a multi-megabyte commit. ``pull``/``commit`` return
    exactly what the local classes return, so HostAsyncRunner cannot tell
    the difference.

    ``codec=`` requests a wire codec in the hello handshake; the server
    answers with what it granted (``.negotiated``; falls back to "raw"
    when the server lacks the codec). Lossy codecs apply error feedback
    to commits inside the tree codec (comms/codec.py).

    Transport faults are survived, not surfaced (DESIGN.md §13): a failed
    round-trip tears the connection down (failing every pipelined waiter,
    who each retry), reconnects with exponential backoff + seeded jitter
    (``retry=``), re-plays the hello handshake, and re-sends. Commits
    carry a client-generated ``(cid, seq)`` identity the server dedups
    on, so "applied but the reply was lost" folds exactly once. When the
    budget is exhausted the caller gets a typed :class:`PSUnavailable` —
    the signal HostAsyncRunner's degradation ladder keys on.
    """

    #: elastic-aware transport: host_async stamps worker identity and
    #: window duration into commits when this is True (the in-process
    #: ParameterServer classes are not on the membership plane).
    elastic = True

    def __init__(self, address: str, like, timeout: float = 600.0,
                 token: Optional[str] = None, codec: str = "raw",
                 retry: Optional[comms.RetryPolicy] = None,
                 op_timeout: Optional[float] = None):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        # per-op reply deadline: a vanished peer becomes a retry after
        # this long, instead of a hang for the full connect timeout
        self._op_timeout = float(op_timeout) if op_timeout else \
            float(timeout)
        self.codec = _TreeCodec(like)
        self.token = token
        self.retry = retry if retry is not None else comms.DEFAULT_RETRY
        self._requested = comms.get_codec(codec).name
        self.negotiated = "raw"
        self._send_lock = threading.Lock()
        self._recv_cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._sock: Optional[socket.socket] = None
        self._gen = 0  # bumped on every teardown: stale waiters see it
        self._ever_connected = False
        self._ctrl_sock: Optional[socket.socket] = None
        self._ctrl_lock = threading.Lock()
        self._closed = False
        # commit identity: one cid per client process, a fresh seq per
        # LOGICAL commit — every retry (and every shard, via the sharded
        # client) re-uses the same (cid, seq); that identity is what the
        # server's dedup cache folds once
        self.cid = os.urandom(8).hex()
        self._seq = 0
        self._seq_lock = threading.Lock()
        with self._send_lock:
            self._ensure_connected()  # fail fast on a bad address

    def next_seq(self) -> int:
        """Allocate the next logical-commit sequence number (shared by
        every shard of one commit in the sharded client)."""
        with self._seq_lock:
            self._seq += 1
            return self._seq

    # -- connection lifecycle ---------------------------------------------
    def _ensure_connected(self) -> None:
        """(Re)open the data connection; caller holds ``_send_lock``."""
        if self._closed:
            raise PSUnavailable(
                f"client for {self._addr[0]}:{self._addr[1]} is closed")
        if self._sock is not None:
            return
        if not self._ever_connected:
            self._connect_locked()
            self._ever_connected = True
            return
        # a RE-connect: visible as a tagged child span when the enclosing
        # rpc is traced (same trace_id), and always as a counter
        with telemetry.span("trace.reconnect"):
            self._connect_locked()
        telemetry.counter("remote_ps.client.reconnects").inc()
        telemetry.record_event("wire", outcome="reconnect",
                               peer=f"{self._addr[0]}:{self._addr[1]}")

    def _connect_locked(self) -> None:
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            if self._requested != "raw":
                # re-play the codec handshake on every (re)connect: the
                # server starts each fresh connection on the raw codec
                hello = {"op": "hello", "codec": self._requested}
                if self.token is not None:
                    hello["token"] = self.token
                # dktlint: disable=lock-blocking-call
                _sendall(sock, hello)
                resp, _ = _recv(sock)  # dktlint: disable=lock-blocking-call
                if "error" in resp:
                    raise ConnectionError(
                        f"hello refused: {resp['error']}")
                granted = resp.get("codec", "raw")
                if granted != self.negotiated:
                    # set_wire resets error-feedback state — only on an
                    # actual codec change, never on a plain reconnect
                    self.negotiated = granted
                    self.codec.set_wire(granted)
        except BaseException:
            sock.close()
            raise
        self._sock = sock

    def _teardown_locked(self) -> None:
        """Close the data connection and fail every pipelined waiter;
        caller holds ``_send_lock``. The generation bump is how waiters
        blocked in ``_roundtrip_once`` learn their reply will never come
        (their retry loop reconnects and re-sends)."""
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        with self._recv_cv:
            self._gen += 1
            self._pending.clear()
            self._recv_cv.notify_all()

    def _teardown(self, gen: int) -> None:
        with self._send_lock:
            if self._gen == gen:  # lost the race: someone already did
                self._teardown_locked()

    # -- round-trips ------------------------------------------------------
    def _roundtrip_once(self, header: dict, blobs,
                        timeout: float) -> Tuple[dict, list]:
        ticket = object()
        with self._send_lock:
            self._ensure_connected()
            sock, gen = self._sock, self._gen
            act = fault.chaos("remote_ps.send")
            if act is not None and act.action == "delay":
                time.sleep(act.delay_s)  # dktlint: disable=lock-blocking-call
            if act is not None and act.action == "reset":
                self._teardown_locked()
                raise ConnectionError("chaos: connection reset before send")
            dropped = act is not None and act.action == "drop"
            if not dropped:
                # enqueue BEFORE releasing the send lock: wire order and
                # waiter order must agree or responses would cross-match.
                # Sending under the lock is the point: it serializes
                # frames on the shared socket (pipelining is recv-side).
                # dktlint: disable=lock-blocking-call
                _sendall(sock, header, blobs)
                if act is not None and act.action == "reset_after_send":
                    # the request DID reach the wire: the server applies
                    # it and replies into a closed socket — the dedup
                    # scenario
                    self._teardown_locked()
                    raise ConnectionError(
                        "chaos: connection reset after send")
                with self._recv_cv:
                    self._pending.append(ticket)
        if dropped:
            # a swallowed request never gets a ticket: FIFO reply matching
            # cannot survive selective loss on a live stream, so the drop
            # rides out the op timeout and then declares the connection
            # dead (which is what a real lost frame amounts to here)
            time.sleep(min(timeout, 5.0) if timeout else 1.0)
            self._teardown(gen)
            raise socket.timeout("chaos: request dropped")
        with self._recv_cv:
            while not (self._pending and self._pending[0] is ticket):
                if self._gen != gen or ticket not in self._pending:
                    raise ConnectionError(
                        "connection torn down while awaiting reply")
                self._recv_cv.wait(timeout=1.0)
        # head of the pipeline: this thread owns the next reply
        try:
            sock.settimeout(timeout)
            resp, rblobs = _recv(sock)
        except (ConnectionError, socket.timeout, OSError):
            self._teardown(gen)
            raise
        with self._recv_cv:
            if self._gen == gen:
                self._pending.popleft()
                self._recv_cv.notify_all()
        return resp, rblobs

    def _roundtrip(self, header: dict, blobs=(),
                   timeout: Optional[float] = None) -> Tuple[dict, list]:
        if telemetry.current_trace() is None:
            return self._roundtrip_impl(header, blobs, timeout)
        # one trace.rpc span per LOGICAL round-trip (retries are child
        # spans inside it, never fresh rpc spans); the span's own context
        # is what gets injected into the wire header below
        with telemetry.span("trace.rpc", op=header.get("op", "?")):
            return self._roundtrip_impl(header, blobs, timeout)

    def _roundtrip_impl(self, header: dict, blobs=(),
                        timeout: Optional[float] = None) -> Tuple[dict, list]:
        op = header.get("op", "?")
        # inject ONCE, outside the retry loop: every re-send of this
        # logical request carries the same traceparent, so the server side
        # of a retried commit lands under the same parent span. Old peers
        # ignore unknown header keys — raw-fallback-safe.
        header = telemetry.inject(dict(header))
        if self.token is not None:
            header["token"] = self.token
        timeout = self._op_timeout if timeout is None else timeout
        t0 = time.perf_counter()
        attempt = 0
        while True:
            try:
                resp, rblobs = self._roundtrip_once(header, blobs, timeout)
                break
            except (ConnectionError, socket.timeout, OSError) as e:
                if self._closed:
                    raise PSUnavailable(
                        f"client for {self._addr[0]}:{self._addr[1]} is "
                        f"closed") from e
                attempt += 1
                if attempt > self.retry.max_retries:
                    telemetry.counter("remote_ps.client.unavailable",
                                      op=op).inc()
                    telemetry.record_event("wire", outcome="unavailable",
                                           op=op, attempts=attempt,
                                           error=str(e)[:200])
                    raise PSUnavailable(
                        f"parameter service {self._addr[0]}:"
                        f"{self._addr[1]} unavailable: {op} failed after "
                        f"{self.retry.max_retries} retries ({e})") from e
                telemetry.counter("remote_ps.client.retries", op=op).inc()
                telemetry.record_event("wire", outcome="retry", op=op,
                                       attempt=attempt)
                with telemetry.span("trace.retry", op=op, attempt=attempt):
                    time.sleep(self.retry.delay(attempt))
        # rtt includes the wait for the shared connection: the contention
        # profile of the one-socket-per-process design is part of what a
        # STALENESS round wants to see
        telemetry.histogram("remote_ps.client.rtt_s",
                            op=op).record(time.perf_counter() - t0)
        telemetry.counter("remote_ps.client.bytes_sent").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("remote_ps.client.bytes_received").inc(
            sum(len(b) for b in rblobs))
        telemetry.counter("comms.bytes_sent", op=op, side="client").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("comms.bytes_recv", op=op, side="client").inc(
            sum(len(b) for b in rblobs))
        if "error" in resp:
            if resp.get("error_kind") == "history-timeout":
                raise HistoryBarrierTimeout(resp["error"])
            if resp.get("error_kind") == "fenced":
                raise CoordinatorFenced(resp["error"],
                                        resp.get("coordinator"),
                                        resp.get("epoch", 0))
            raise RuntimeError(f"parameter service: {resp['error']}")
        return resp, rblobs

    def _control_once(self, header: dict, timeout: float) -> dict:
        # the control channel is intentionally one-request-at-a-time: the
        # lock held over connect/send/recv IS the serialization (only
        # small header-only frames travel here, bounded by the timeout)
        with self._ctrl_lock:
            if self._closed:
                raise PSUnavailable(
                    f"client for {self._addr[0]}:{self._addr[1]} is closed")
            if self._ctrl_sock is None:
                # dktlint: disable=lock-blocking-call
                self._ctrl_sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                self._ctrl_sock.setsockopt(socket.IPPROTO_TCP,
                                           socket.TCP_NODELAY, 1)
            try:
                self._ctrl_sock.settimeout(timeout)
                _sendall(self._ctrl_sock, header)  # dktlint: disable=lock-blocking-call
                resp, _ = _recv(self._ctrl_sock)  # dktlint: disable=lock-blocking-call
            except (ConnectionError, socket.timeout, OSError):
                try:
                    self._ctrl_sock.close()
                except OSError:
                    pass
                self._ctrl_sock = None
                raise
        if "error" in resp:
            if resp.get("error_kind") == "fenced":
                raise CoordinatorFenced(resp["error"],
                                        resp.get("coordinator"),
                                        resp.get("epoch", 0))
            raise RuntimeError(f"parameter service: {resp['error']}")
        return resp

    def _control_roundtrip(self, header: dict,
                           timeout: Optional[float] = None) -> dict:
        """Small blob-free ops on a dedicated connection (opened on first
        use): a clock poll answers in one small-packet RTT even while the
        data connection is mid-way through a large commit. Same bounded
        reconnect/backoff as the data path."""
        op = header.get("op", "?")
        if self.token is not None:
            header = dict(header, token=self.token)
        timeout = self._op_timeout if timeout is None else timeout
        attempt = 0
        while True:
            try:
                return self._control_once(header, timeout)
            except PSUnavailable:
                raise
            except (ConnectionError, socket.timeout, OSError) as e:
                attempt += 1
                if attempt > self.retry.max_retries:
                    telemetry.counter("remote_ps.client.unavailable",
                                      op=op).inc()
                    telemetry.record_event("wire", outcome="unavailable",
                                           op=op, attempts=attempt,
                                           error=str(e)[:200])
                    raise PSUnavailable(
                        f"parameter service {self._addr[0]}:"
                        f"{self._addr[1]} unavailable: {op} failed after "
                        f"{self.retry.max_retries} retries ({e})") from e
                telemetry.counter("remote_ps.client.retries", op=op).inc()
                telemetry.record_event("wire", outcome="retry", op=op,
                                       attempt=attempt)
                time.sleep(self.retry.delay(attempt))

    # -- ParameterServer interface ----------------------------------------
    def pull(self):
        resp, blobs = self._roundtrip({"op": "pull"})
        return self.codec.decode(blobs, kind="pull"), resp["clock"]

    def pull_versioned(self):
        """(center, clock, model_version): the rollout controller's poll
        primitive — one roundtrip, version stamped by the same reply."""
        resp, blobs = self._roundtrip({"op": "pull"})
        return (self.codec.decode(blobs, kind="pull"), resp["clock"],
                int(resp.get("model_version", 0)))

    def commit(self, delta: Any, last_update: int = 0, **kw) -> int:
        return self.commit_ex(delta, last_update=last_update, **kw)[0]

    def commit_ex(self, delta: Any, last_update: int = 0, weight=None,
                  seq: Optional[int] = None, worker: Optional[int] = None,
                  window_s: Optional[float] = None) -> tuple:
        """Commit with the applied fold weight surfaced; returns
        ``(at_fold, weight)``. The delta is encoded ONCE, before the
        retry loop — a lossy codec's error-feedback state must not be
        double-charged by a re-send of the same logical commit."""
        header = {"op": "commit", "last_update": int(last_update),
                  "cid": self.cid,
                  "seq": int(seq) if seq is not None else self.next_seq()}
        if weight is not None:
            header["weight"] = float(weight)
        if worker is not None:
            header["worker"] = int(worker)
        if window_s is not None:
            header["window_s"] = float(window_s)
        resp, _ = self._roundtrip(header,
                                  self.codec.encode(delta, kind="commit"))
        return resp["at_fold"], resp.get("weight", 1.0)

    @property
    def num_updates(self) -> int:
        return self._control_roundtrip({"op": "clock"})["clock"]

    @property
    def model_version(self) -> int:
        """The published deployment version (serving/rollout.py) — a
        header-only control roundtrip, no center transfer."""
        return int(self._control_roundtrip({"op": "version"})["version"])

    def set_model_version(self, version: int) -> None:
        """Stamp a publish onto the remote center (WeightPublisher's
        remote leg); the server enforces monotonicity."""
        self._control_roundtrip({"op": "version", "set": int(version)})

    # -- elastic membership (coordinator shard only; DESIGN.md §13) -------
    def register(self, worker: int,
                 lease_s: Optional[float] = None) -> float:
        """Join the fleet; returns the granted lease in seconds (0.0 when
        the peer runs no membership plane)."""
        header = {"op": "register", "worker": int(worker)}
        if lease_s is not None:
            header["lease_s"] = float(lease_s)
        return float(self._control_roundtrip(header)["lease_s"])

    def renew_lease(self, worker: int) -> bool:
        """Heartbeat the lease; True means the coordinator has this
        worker marked evicted (its next commit will late-fold)."""
        return bool(self._control_roundtrip(
            {"op": "lease_renew", "worker": int(worker)})["evicted"])

    def deregister(self, worker: int) -> None:
        self._control_roundtrip({"op": "deregister", "worker": int(worker)})

    def shard_map(self) -> dict:
        """The fleet layout as the peer knows it:
        ``{shard, num_shards, addresses}`` (late-joiner bootstrap)."""
        return self._control_roundtrip({"op": "shard_map"})

    # -- coordinator failover (DESIGN.md §17) ------------------------------
    def coordinator_view(self) -> dict:
        """Who holds the coordinator lease, per this peer. Asking a
        STANDBY is the failure detector: a lapsed coordinator lease is
        noticed (and promotion performed) while this query is answered."""
        return self._control_roundtrip({"op": "coordinator"})

    def promote(self, force: bool = False) -> dict:
        """Ask a standby to promote (``force=True`` skips the lease-lapse
        check — deterministic handoffs in tests and failover drills).
        Returns ``{promoted, epoch, reason, address}``; a standby that
        already promoted rejects the second promotion."""
        return self._control_roundtrip({"op": "promote",
                                        "force": bool(force)})

    # -- end-of-run history barrier ---------------------------------------
    def put_history(self, pid: int, windows: list) -> None:
        self._roundtrip({"op": "history_put", "pid": int(pid),
                         "windows": [[int(c), float(s), steps]
                                     for c, s, steps in windows]})

    def get_history(self, timeout: float = 600):
        # reply deadline = the server-side barrier timeout plus transport
        # slack; a barrier failure arrives as a typed HistoryBarrierTimeout
        resp, blobs = self._roundtrip({"op": "history_get",
                                       "timeout": timeout},
                                      timeout=timeout + 30.0)
        return (resp["windows"], self.codec.decode(blobs, kind="pull"),
                resp["clock"])

    # -- fleet telemetry (collector on the coordinator shard) --------------
    def put_telemetry(self, pid: int, rows: list) -> dict:
        """Push this process's telemetry rows to the coordinator's
        collector. Best-effort BY DESIGN: telemetry must never fail a run,
        so an old peer (unknown op) or an unreachable service comes back
        as ``{"ok": False}`` instead of an exception."""
        try:
            resp, _ = self._roundtrip({"op": "telemetry_put",
                                       "pid": int(pid),
                                       "rows": list(rows)})
        except (PSUnavailable, RuntimeError):
            return {"ok": False, "accepted": 0, "dropped": 0}
        return resp

    def get_merged_telemetry(self) -> list:
        """The coordinator's merged fleet rows, each tagged with its
        origin ``pid``; [] when the peer mounts no collector."""
        resp, _ = self._roundtrip({"op": "telemetry_merged"})
        return resp.get("rows", [])

    def close(self) -> None:
        """Idempotent teardown (runner exit AND test teardown may both
        call it). The control connection is closed even if a control
        round-trip is in flight: the lock acquire is bounded, and closing
        the socket out from under the op fails it fast instead of holding
        close() hostage for the op's full timeout."""
        if self._closed:
            return
        self._closed = True
        with self._send_lock:
            self._teardown_locked()
        got = self._ctrl_lock.acquire(timeout=1.0)
        try:
            sock, self._ctrl_sock = self._ctrl_sock, None
        finally:
            if got:
                self._ctrl_lock.release()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # reference lifecycle no-ops (parity with ParameterServer)
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


def share_service_address(ports,
                          token: Optional[str] = None,
                          error: bool = False) -> Tuple[str, Optional[str]]:
    """Agree on the service address AND auth token across processes:
    process 0 broadcasts ``host:port|token`` through a tiny collective;
    everyone returns the same ``(address, token)`` pair.

    ``ports`` may be a single port or a sequence of them (a shard fleet,
    DESIGN.md §13): the broadcast payload is then the full shard map,
    ``host:p0,host:p1,...|token`` in shard order — a single shard
    produces byte-for-byte the single-server payload, so N=1 stays
    wire-compatible. Callers split the returned address on ``","``.

    Entries that are already STRINGS pass through verbatim (DESIGN.md
    §17): spread placement broadcasts full cross-host ``host:port``
    addresses gathered from every hosting process, and the designated
    standby rides the same payload as a ``~host:port`` entry — old
    callers that pass bare ports see byte-identical payloads. An EMPTY
    ``ports`` list broadcasts just the token (``|token``): the
    token-first handshake spread placement needs before any process can
    bind an authenticated service.

    ``error=True`` (process 0 only) broadcasts a failure sentinel instead —
    the symmetric-agreement half of service construction (ADVICE r5): if
    process 0 could not bring the service up, its peers RAISE here instead
    of blocking in this broadcast until the collective timeout. Peers raise;
    process 0 returns a dummy so its own (real) exception propagates.
    """
    from jax.experimental import multihost_utils

    from distkeras_tpu.parallel.distributed import determine_host_address

    port_list = list(ports) if isinstance(ports, (list, tuple)) \
        else [ports]
    if jax.process_count() == 1:
        return ",".join(e if isinstance(e, str) else f"127.0.0.1:{e}"
                        for e in port_list), token
    payload = np.zeros((512,), np.uint8)  # sized for a multi-shard map
    if jax.process_index() == 0:
        host = determine_host_address()
        msg = ("!service construction failed on process 0" if error
               else ",".join(e if isinstance(e, str) else f"{host}:{e}"
                             for e in port_list)
               + f"|{token or ''}")
        raw = msg.encode()
        if len(raw) > payload.size:
            raise ValueError(f"payload {raw!r} longer than "
                             f"{payload.size} bytes")
        payload[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(payload))
    msg = bytes(out[out != 0]).decode()
    if msg.startswith("!"):
        if jax.process_index() == 0:
            return "", None  # the original exception is already in flight
        raise RuntimeError(f"parameter service never came up: {msg[1:]}")
    addr, _, tok = msg.partition("|")
    return addr, (tok or None)
