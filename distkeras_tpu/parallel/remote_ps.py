"""Cross-process TRUE-async: a live parameter service over the pod fabric.

Reference parity: dist-keras's defining deployment is workers on SEPARATE
machines training against a live parameter server on the driver
(``distkeras/parameter_servers.py``/``networking.py`` — unverified, mount
empty): a socket server, per-connection handler threads, and pickled
center/delta dicts on the wire. This module is that topology rebuilt for a
TPU pod (VERDICT r4 ask #2):

- process 0's **device-resident** ParameterServer (parameter_servers.py —
  center in HBM, jitted folds) is fronted by :class:`ParameterServerService`,
  a socket server with the reference's accept-loop/handler-thread shape;
- every process's HostAsyncRunner worker threads pull/commit through
  :class:`RemoteParameterServer`, a drop-in for the ParameterServer
  interface (process 0's workers talk to the object directly — no loopback
  tax on the host that owns the center);
- the wire is length-prefixed JSON headers + raw array bytes — **no
  pickle**: nothing on the wire can execute code, and leaves decode
  zero-copy into numpy. It rides whatever IP fabric connects the hosts
  (DCN on a pod, loopback in the two-process tests).

Staleness here is REAL: commits from different hosts interleave at the
center in wall-clock order, and each commit's staleness is the server
clock distance since that worker's pull — across processes, not just
across threads.

End-of-run bookkeeping rides the same wire: each process uploads its
(commit-clock-tagged) window records; ``history_get`` blocks until every
process has uploaded, then returns the clock-merged history plus the
final center — so all processes finish with identical history and params,
matching the sync path's process-transparency.
"""

from __future__ import annotations

import collections
import json
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np

from distkeras_tpu import comms, telemetry
from distkeras_tpu.health.endpoints import HEALTH_OPS, handle_health_op
from distkeras_tpu.parameter_servers import ParameterServer
from distkeras_tpu.utils.fetch import device_get_batched


# -- wire format -----------------------------------------------------------
# [u32 header_len][header JSON (utf-8)][blob 0][blob 1]...
# header["blob_lens"] carries the byte length of each trailing blob.
# Public: the serving front-end (distkeras_tpu/serving/server.py) speaks
# the same framing and the same token scheme.
#
# Blob CONTENT is codec-dependent (comms/codec.py): a connection starts on
# the raw codec and may switch after a {"op": "hello", "codec": ...}
# handshake — the server grants the request when it supports that codec and
# answers with the accepted name (fallback: "raw"), after which both ends
# encode/decode every pull/commit blob through it.

def send_message(sock: socket.socket, header: dict,
                 blobs: Sequence = ()):
    """Frame and send. Blobs may be bytes or memoryviews; large ones go out
    as bounded chunks straight from their backing arrays (no whole-message
    join — the old ``b"".join`` copied every leaf a second time)."""
    header = dict(header)
    header["blob_lens"] = [len(b) for b in blobs]
    hb = json.dumps(header).encode()
    sock.sendall(struct.pack("<I", len(hb)) + hb)
    comms.send_buffers(sock, blobs)


def _recvexact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return bytes(buf)


def recv_message(sock: socket.socket) -> Tuple[dict, list]:
    (hlen,) = struct.unpack("<I", _recvexact(sock, 4))
    header = json.loads(_recvexact(sock, hlen))
    blobs = [_recvexact(sock, n) for n in header.get("blob_lens", [])]
    return header, blobs


_sendall = send_message  # internal aliases, kept for brevity below
_recv = recv_message


def check_token(expected: Optional[str], header: dict) -> bool:
    """Constant-time shared-token check (ADVICE r5): the service refuses
    any request whose header token does not match the process-0-generated
    secret. ``expected=None`` disables authentication (single-host dev)."""
    if expected is None:
        return True
    import hmac

    got = header.get("token")
    return isinstance(got, str) and hmac.compare_digest(got, expected)


class _TreeCodec:
    """Flatten/unflatten a fixed pytree structure to wire leaf blobs.

    Both ends construct the codec from their own (identically-initialized)
    params tree, so the wire carries only leaf blobs — structure, shapes
    and dtypes are agreed out of band and VERIFIED on decode. The per-leaf
    encoding is delegated to a pluggable wire codec (comms/codec.py,
    default raw); lossy codecs get a worker-side error-feedback accumulator
    so commit quantization error re-enters the next delta instead of being
    lost.
    """

    def __init__(self, like, wire="raw"):
        host = jax.tree.map(np.asarray, device_get_batched(like))
        leaves, self.treedef = jax.tree_util.tree_flatten(host)
        self.specs = [(l.shape, l.dtype) for l in leaves]
        self._raw_bytes = sum(
            int(np.prod(s)) * np.dtype(d).itemsize for s, d in self.specs)
        self.set_wire(wire)

    def set_wire(self, wire) -> None:
        self.wire = comms.get_codec(wire)
        self._ef = comms.ErrorFeedback(self.wire) if self.wire.lossy \
            else None

    def with_wire(self, wire) -> "_TreeCodec":
        """A sibling sharing the (immutable) specs/treedef with its own
        wire codec + error-feedback state — per-connection codecs on the
        server without re-flattening ``like`` per accept."""
        clone = object.__new__(_TreeCodec)
        clone.treedef = self.treedef
        clone.specs = self.specs
        clone._raw_bytes = self._raw_bytes
        clone.set_wire(wire)
        return clone

    def encode(self, tree, kind: str = "commit") -> list:
        leaves = [np.asarray(l) for l in jax.tree_util.tree_flatten(
            device_get_batched(tree))[0]]
        if len(leaves) != len(self.specs):
            raise ValueError(
                f"tree has {len(leaves)} leaves, codec expects "
                f"{len(self.specs)}")
        if self._ef is not None and kind == "commit":
            blobs = self._ef.encode_leaves(leaves, self.specs)
        else:
            blobs = [self.wire.encode(l, kind=kind) for l in leaves]
        wire_bytes = sum(len(b) for b in blobs)
        if wire_bytes:
            telemetry.histogram("comms.compress_ratio", op=kind,
                                codec=self.wire.name).record(
                self._raw_bytes / wire_bytes)
        return blobs

    def decode(self, blobs: Sequence[bytes], kind: str = "commit"):
        if len(blobs) != len(self.specs):
            raise ValueError(
                f"message has {len(blobs)} blobs, codec expects "
                f"{len(self.specs)}")
        leaves = [self.wire.decode(b, shape, dtype, kind=kind)
                  for b, (shape, dtype) in zip(blobs, self.specs)]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class ParameterServerService:
    """Socket front-end for a live ParameterServer (runs on process 0).

    The reference's lifecycle verbs (``start``/``run``/``stop``) and
    thread shape (accept loop + handler thread per connection) are kept;
    the center behind the socket is device-resident and its folds are the
    jitted commits of parameter_servers.py. Also aggregates end-of-run
    window histories from every process (``history_put``/``history_get``).
    """

    def __init__(self, ps: ParameterServer, like,
                 expected_processes: int = 1,
                 host: str = "0.0.0.0", port: int = 0,
                 token: Optional[str] = None,
                 codecs: Optional[Sequence[str]] = None):
        self.ps = ps
        self.codec = _TreeCodec(like)
        # wire codecs this server will grant in the hello handshake
        # (None = everything registered); raw is always granted
        self.supported = tuple(codecs) if codecs is not None \
            else comms.available_codecs()
        self.expected = int(expected_processes)
        self.token = token  # ADVICE r5: required in every request header
        self._histories: dict[int, list] = {}
        self._hist_cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._t_start = time.time()
        self._threads: list = []

    # -- lifecycle (reference vocabulary) ---------------------------------
    def start(self) -> None:
        self._running = True
        self._t_start = time.time()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            # prune finished handlers (ADVICE r5): the list otherwise grows
            # one entry per connection for the life of the service
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    # -- per-connection handler (reference: handle_connection) ------------
    def _serve(self, conn: socket.socket):
        inflight = telemetry.gauge("remote_ps.server.inflight_connections")
        inflight.add(1)
        codec = self.codec  # per-connection: hello may swap the wire codec
        try:
            with conn:
                while True:
                    try:
                        header, blobs = _recv(conn)
                    except ConnectionError:
                        return
                    if not check_token(self.token, header):
                        telemetry.counter(
                            "remote_ps.server.auth_failures").inc()
                        _sendall(conn, {"error": "authentication failed"})
                        return  # drop the connection, not just the request
                    if header["op"] == "hello":
                        granted = comms.negotiate(
                            header.get("codec", "raw"), self.supported)
                        codec = self.codec.with_wire(granted)
                        telemetry.counter("comms.negotiated",
                                          codec=granted).inc()
                        _sendall(conn, {"codec": granted})
                        continue
                    self._dispatch(conn, header, blobs, codec)
        except Exception:
            if self._running:  # surface handler crashes, don't die silently
                raise
        finally:
            inflight.add(-1)

    def _dispatch(self, conn, header: dict, blobs: list,
                  codec: Optional[_TreeCodec] = None):
        op = header["op"]
        telemetry.counter("remote_ps.server.dispatch", op=op).inc()
        telemetry.counter("remote_ps.server.bytes_received").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("comms.bytes_recv", op=op, side="server").inc(
            sum(len(b) for b in blobs))
        t0 = time.perf_counter()
        try:
            self._dispatch_op(conn, op, header, blobs,
                              codec if codec is not None else self.codec)
        finally:
            telemetry.histogram("remote_ps.server.handle_s",
                                op=op).record(time.perf_counter() - t0)

    @staticmethod
    def _reply(conn, op: str, header: dict, blobs: Sequence = ()):
        telemetry.counter("comms.bytes_sent", op=op, side="server").inc(
            sum(len(b) for b in blobs))
        _sendall(conn, header, blobs)

    def _dispatch_op(self, conn, op: str, header: dict, blobs: list,
                     codec: _TreeCodec):
        if op == "pull":
            center, clock = self.ps.pull()
            self._reply(conn, op, {"clock": clock},
                        codec.encode(center, kind="pull"))
        elif op == "commit":
            # decode ONCE into the leaves' native dtypes; the PS folds the
            # decoded tree directly (no second materialization)
            delta = codec.decode(blobs, kind="commit")
            at_fold = self.ps.commit(delta,
                                     last_update=header["last_update"])
            self._reply(conn, op, {"at_fold": at_fold})
        elif op == "clock":
            self._reply(conn, op, {"clock": self.ps.pull()[1]})
        elif op == "history_put":
            with self._hist_cv:
                self._histories[int(header["pid"])] = header["windows"]
                self._hist_cv.notify_all()
            self._reply(conn, op, {"ok": True})
        elif op == "history_get":
            # blocks until EVERY process uploaded — the end-of-run barrier.
            # The timeout reply is sent AFTER the cv is released: a socket
            # send under self._hist_cv would freeze every history_put
            # worker behind a slow reader's TCP window for the full I/O
            # wait (dktlint: lock-blocking-call).
            with self._hist_cv:
                self._hist_cv.wait_for(
                    lambda: len(self._histories) >= self.expected,
                    timeout=header.get("timeout", 600))
                uploaded = sorted(self._histories)
                merged = sorted(
                    (w for ws in self._histories.values() for w in ws),
                    key=lambda w: w[0])
            if len(uploaded) < self.expected:
                _sendall(conn, {"error": "history barrier timeout: "
                                f"{uploaded} of "
                                f"{self.expected} processes uploaded"})
                return
            center, clock = self.ps.pull()
            self._reply(conn, op, {"windows": merged, "clock": clock},
                        codec.encode(center, kind="pull"))
        elif op in HEALTH_OPS:
            # live health plane (DESIGN.md §9): header-only introspection
            # sharing this connection's framing + token auth
            with self._hist_cv:
                uploaded = len(self._histories)
            self._reply(conn, op, handle_health_op(op, header, extra_status={
                "service": "parameter_server",
                "clock": int(self.ps.num_updates),  # no center fetch
                "expected_processes": self.expected,
                "histories_uploaded": uploaded,
                "uptime_s": round(time.time() - self._t_start, 3),
                "port": self.port,
            }))
        else:
            _sendall(conn, {"error": f"unknown op {op!r}"})

    # -- direct (in-process) counterparts for process 0 -------------------
    def put_history(self, pid: int, windows: list) -> None:
        with self._hist_cv:
            self._histories[int(pid)] = [
                [int(c), float(s), steps] for c, s, steps in windows]
            self._hist_cv.notify_all()

    def get_history_blocking(self, timeout: float = 600):
        with self._hist_cv:
            ok = self._hist_cv.wait_for(
                lambda: len(self._histories) >= self.expected,
                timeout=timeout)
            if not ok:
                raise TimeoutError(
                    f"history barrier: {sorted(self._histories)} of "
                    f"{self.expected} processes uploaded")
            merged = sorted(
                (w for ws in self._histories.values() for w in ws),
                key=lambda w: w[0])
        center, clock = self.ps.pull()
        return merged, device_get_batched(center), clock


class RemoteParameterServer:
    """Client drop-in for the ParameterServer interface over the service.

    One data connection per process; worker threads share it PIPELINED:
    the connection lock covers only the send, and responses are claimed in
    send order by a FIFO of waiters — so a worker's request goes on the
    wire as soon as the previous request finished *sending*, not after its
    full round-trip (the old full-RPC lock made every small request pay
    the largest in-flight commit's RTT, and vice versa). Control-plane ops
    (``num_updates`` polls) ride a separate lazily-opened connection with
    its own server handler thread, so they can never head-of-line-block —
    or be blocked by — a multi-megabyte commit. ``pull``/``commit`` return
    exactly what the local classes return, so HostAsyncRunner cannot tell
    the difference.

    ``codec=`` requests a wire codec in the hello handshake; the server
    answers with what it granted (``.negotiated``; falls back to "raw"
    when the server lacks the codec). Lossy codecs apply error feedback
    to commits inside the tree codec (comms/codec.py).
    """

    def __init__(self, address: str, like, timeout: float = 600.0,
                 token: Optional[str] = None, codec: str = "raw"):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self.codec = _TreeCodec(like)
        self.token = token
        self._sock = socket.create_connection(self._addr, timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._recv_cv = threading.Condition()
        self._pending: collections.deque = collections.deque()
        self._ctrl_sock: Optional[socket.socket] = None
        self._ctrl_lock = threading.Lock()
        self.negotiated = "raw"
        if comms.get_codec(codec).name != "raw":
            resp, _ = self._roundtrip({"op": "hello",
                                       "codec": comms.get_codec(codec).name})
            self.negotiated = resp["codec"]
            self.codec.set_wire(self.negotiated)

    def _roundtrip(self, header: dict, blobs=()) -> Tuple[dict, list]:
        op = header.get("op", "?")
        if self.token is not None:
            header = dict(header, token=self.token)
        t0 = time.perf_counter()
        ticket = object()
        with self._send_lock:
            # enqueue BEFORE releasing the send lock: wire order and
            # waiter order must agree or responses would cross-match.
            # Sending under the lock is the point: it serializes frames on
            # the shared socket (pipelining happens at the recv side).
            # dktlint: disable=lock-blocking-call
            _sendall(self._sock, header, blobs)
            with self._recv_cv:
                self._pending.append(ticket)
        with self._recv_cv:
            while self._pending[0] is not ticket:
                self._recv_cv.wait()
        try:
            resp, rblobs = _recv(self._sock)
        finally:
            with self._recv_cv:
                self._pending.popleft()
                self._recv_cv.notify_all()
        # rtt includes the wait for the shared connection: the contention
        # profile of the one-socket-per-process design is part of what a
        # STALENESS round wants to see
        telemetry.histogram("remote_ps.client.rtt_s",
                            op=op).record(time.perf_counter() - t0)
        telemetry.counter("remote_ps.client.bytes_sent").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("remote_ps.client.bytes_received").inc(
            sum(len(b) for b in rblobs))
        telemetry.counter("comms.bytes_sent", op=op, side="client").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("comms.bytes_recv", op=op, side="client").inc(
            sum(len(b) for b in rblobs))
        if "error" in resp:
            raise RuntimeError(f"parameter service: {resp['error']}")
        return resp, rblobs

    def _control_roundtrip(self, header: dict) -> dict:
        """Small blob-free ops on a dedicated connection (opened on first
        use): a clock poll answers in one small-packet RTT even while the
        data connection is mid-way through a large commit."""
        if self.token is not None:
            header = dict(header, token=self.token)
        # the control channel is intentionally one-request-at-a-time: the
        # lock held over connect/send/recv IS the serialization (only
        # small header-only frames travel here, bounded by self._timeout)
        with self._ctrl_lock:
            if self._ctrl_sock is None:
                # dktlint: disable=lock-blocking-call
                self._ctrl_sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                self._ctrl_sock.setsockopt(socket.IPPROTO_TCP,
                                           socket.TCP_NODELAY, 1)
            _sendall(self._ctrl_sock, header)  # dktlint: disable=lock-blocking-call
            resp, _ = _recv(self._ctrl_sock)  # dktlint: disable=lock-blocking-call
        if "error" in resp:
            raise RuntimeError(f"parameter service: {resp['error']}")
        return resp

    def pull(self):
        resp, blobs = self._roundtrip({"op": "pull"})
        return self.codec.decode(blobs, kind="pull"), resp["clock"]

    def commit(self, delta: Any, last_update: int = 0) -> int:
        resp, _ = self._roundtrip(
            {"op": "commit", "last_update": int(last_update)},
            self.codec.encode(delta, kind="commit"))
        return resp["at_fold"]

    @property
    def num_updates(self) -> int:
        return self._control_roundtrip({"op": "clock"})["clock"]

    def put_history(self, pid: int, windows: list) -> None:
        self._roundtrip({"op": "history_put", "pid": int(pid),
                         "windows": [[int(c), float(s), steps]
                                     for c, s, steps in windows]})

    def get_history(self, timeout: float = 600):
        resp, blobs = self._roundtrip({"op": "history_get",
                                       "timeout": timeout})
        return (resp["windows"], self.codec.decode(blobs, kind="pull"),
                resp["clock"])

    def close(self) -> None:
        for sock in (self._sock, self._ctrl_sock):
            if sock is None:
                continue
            try:
                sock.close()
            except OSError:
                pass

    # reference lifecycle no-ops (parity with ParameterServer)
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


def share_service_address(port: Optional[int],
                          token: Optional[str] = None,
                          error: bool = False) -> Tuple[str, Optional[str]]:
    """Agree on the service address AND auth token across processes:
    process 0 broadcasts ``host:port|token`` through a tiny collective;
    everyone returns the same ``(address, token)`` pair.

    ``error=True`` (process 0 only) broadcasts a failure sentinel instead —
    the symmetric-agreement half of service construction (ADVICE r5): if
    process 0 could not bring the service up, its peers RAISE here instead
    of blocking in this broadcast until the collective timeout. Peers raise;
    process 0 returns a dummy so its own (real) exception propagates.
    """
    from jax.experimental import multihost_utils

    from distkeras_tpu.parallel.distributed import determine_host_address

    if jax.process_count() == 1:
        return f"127.0.0.1:{port}", token
    payload = np.zeros((192,), np.uint8)
    if jax.process_index() == 0:
        msg = ("!service construction failed on process 0" if error
               else f"{determine_host_address()}:{port}|{token or ''}")
        raw = msg.encode()
        if len(raw) > payload.size:
            raise ValueError(f"payload {raw!r} longer than "
                             f"{payload.size} bytes")
        payload[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(payload))
    msg = bytes(out[out != 0]).decode()
    if msg.startswith("!"):
        if jax.process_index() == 0:
            return "", None  # the original exception is already in flight
        raise RuntimeError(f"parameter service never came up: {msg[1:]}")
    addr, _, tok = msg.partition("|")
    return addr, (tok or None)
