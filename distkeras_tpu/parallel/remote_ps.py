"""Cross-process TRUE-async: a live parameter service over the pod fabric.

Reference parity: dist-keras's defining deployment is workers on SEPARATE
machines training against a live parameter server on the driver
(``distkeras/parameter_servers.py``/``networking.py`` — unverified, mount
empty): a socket server, per-connection handler threads, and pickled
center/delta dicts on the wire. This module is that topology rebuilt for a
TPU pod (VERDICT r4 ask #2):

- process 0's **device-resident** ParameterServer (parameter_servers.py —
  center in HBM, jitted folds) is fronted by :class:`ParameterServerService`,
  a socket server with the reference's accept-loop/handler-thread shape;
- every process's HostAsyncRunner worker threads pull/commit through
  :class:`RemoteParameterServer`, a drop-in for the ParameterServer
  interface (process 0's workers talk to the object directly — no loopback
  tax on the host that owns the center);
- the wire is length-prefixed JSON headers + raw array bytes — **no
  pickle**: nothing on the wire can execute code, and leaves decode
  zero-copy into numpy. It rides whatever IP fabric connects the hosts
  (DCN on a pod, loopback in the two-process tests).

Staleness here is REAL: commits from different hosts interleave at the
center in wall-clock order, and each commit's staleness is the server
clock distance since that worker's pull — across processes, not just
across threads.

End-of-run bookkeeping rides the same wire: each process uploads its
(commit-clock-tagged) window records; ``history_get`` blocks until every
process has uploaded, then returns the clock-merged history plus the
final center — so all processes finish with identical history and params,
matching the sync path's process-transparency.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.parameter_servers import ParameterServer
from distkeras_tpu.utils.fetch import device_get_batched


# -- wire format -----------------------------------------------------------
# [u32 header_len][header JSON (utf-8)][blob 0][blob 1]...
# header["blob_lens"] carries the byte length of each trailing blob.
# Public: the serving front-end (distkeras_tpu/serving/server.py) speaks
# the same framing and the same token scheme.

def send_message(sock: socket.socket, header: dict,
                 blobs: Sequence[bytes] = ()):
    header = dict(header)
    header["blob_lens"] = [len(b) for b in blobs]
    hb = json.dumps(header).encode()
    sock.sendall(b"".join([struct.pack("<I", len(hb)), hb, *blobs]))


def _recvexact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return bytes(buf)


def recv_message(sock: socket.socket) -> Tuple[dict, list]:
    (hlen,) = struct.unpack("<I", _recvexact(sock, 4))
    header = json.loads(_recvexact(sock, hlen))
    blobs = [_recvexact(sock, n) for n in header.get("blob_lens", [])]
    return header, blobs


_sendall = send_message  # internal aliases, kept for brevity below
_recv = recv_message


def check_token(expected: Optional[str], header: dict) -> bool:
    """Constant-time shared-token check (ADVICE r5): the service refuses
    any request whose header token does not match the process-0-generated
    secret. ``expected=None`` disables authentication (single-host dev)."""
    if expected is None:
        return True
    import hmac

    got = header.get("token")
    return isinstance(got, str) and hmac.compare_digest(got, expected)


class _TreeCodec:
    """Flatten/unflatten a fixed pytree structure to raw leaf bytes.

    Both ends construct the codec from their own (identically-initialized)
    params tree, so the wire carries only leaf bytes — structure, shapes
    and dtypes are agreed out of band and VERIFIED on decode.
    """

    def __init__(self, like):
        host = jax.tree.map(np.asarray, device_get_batched(like))
        leaves, self.treedef = jax.tree_util.tree_flatten(host)
        self.specs = [(l.shape, l.dtype) for l in leaves]

    def encode(self, tree) -> list:
        leaves = jax.tree_util.tree_flatten(
            jax.tree.map(np.asarray, device_get_batched(tree)))[0]
        if len(leaves) != len(self.specs):
            raise ValueError(
                f"tree has {len(leaves)} leaves, codec expects "
                f"{len(self.specs)}")
        return [np.ascontiguousarray(l).tobytes() for l in leaves]

    def decode(self, blobs: Sequence[bytes]):
        if len(blobs) != len(self.specs):
            raise ValueError(
                f"message has {len(blobs)} blobs, codec expects "
                f"{len(self.specs)}")
        leaves = []
        for b, (shape, dtype) in zip(blobs, self.specs):
            arr = np.frombuffer(b, dtype=dtype)
            if arr.size != int(np.prod(shape)):
                raise ValueError(
                    f"blob of {arr.size} elements does not match leaf "
                    f"shape {shape}")
            leaves.append(arr.reshape(shape))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


class ParameterServerService:
    """Socket front-end for a live ParameterServer (runs on process 0).

    The reference's lifecycle verbs (``start``/``run``/``stop``) and
    thread shape (accept loop + handler thread per connection) are kept;
    the center behind the socket is device-resident and its folds are the
    jitted commits of parameter_servers.py. Also aggregates end-of-run
    window histories from every process (``history_put``/``history_get``).
    """

    def __init__(self, ps: ParameterServer, like,
                 expected_processes: int = 1,
                 host: str = "0.0.0.0", port: int = 0,
                 token: Optional[str] = None):
        self.ps = ps
        self.codec = _TreeCodec(like)
        self.expected = int(expected_processes)
        self.token = token  # ADVICE r5: required in every request header
        self._histories: dict[int, list] = {}
        self._hist_cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._threads: list = []

    # -- lifecycle (reference vocabulary) ---------------------------------
    def start(self) -> None:
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            # prune finished handlers (ADVICE r5): the list otherwise grows
            # one entry per connection for the life of the service
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass

    # -- per-connection handler (reference: handle_connection) ------------
    def _serve(self, conn: socket.socket):
        inflight = telemetry.gauge("remote_ps.server.inflight_connections")
        inflight.add(1)
        try:
            with conn:
                while True:
                    try:
                        header, blobs = _recv(conn)
                    except ConnectionError:
                        return
                    if not check_token(self.token, header):
                        telemetry.counter(
                            "remote_ps.server.auth_failures").inc()
                        _sendall(conn, {"error": "authentication failed"})
                        return  # drop the connection, not just the request
                    self._dispatch(conn, header, blobs)
        except Exception:
            if self._running:  # surface handler crashes, don't die silently
                raise
        finally:
            inflight.add(-1)

    def _dispatch(self, conn, header: dict, blobs: list):
        op = header["op"]
        telemetry.counter("remote_ps.server.dispatch", op=op).inc()
        telemetry.counter("remote_ps.server.bytes_received").inc(
            sum(len(b) for b in blobs))
        t0 = time.perf_counter()
        try:
            self._dispatch_op(conn, op, header, blobs)
        finally:
            telemetry.histogram("remote_ps.server.handle_s",
                                op=op).record(time.perf_counter() - t0)

    def _dispatch_op(self, conn, op: str, header: dict, blobs: list):
        if op == "pull":
            center, clock = self.ps.pull()
            _sendall(conn, {"clock": clock}, self.codec.encode(center))
        elif op == "commit":
            delta = self.codec.decode(blobs)
            at_fold = self.ps.commit(delta,
                                     last_update=header["last_update"])
            _sendall(conn, {"at_fold": at_fold})
        elif op == "clock":
            _sendall(conn, {"clock": self.ps.pull()[1]})
        elif op == "history_put":
            with self._hist_cv:
                self._histories[int(header["pid"])] = header["windows"]
                self._hist_cv.notify_all()
            _sendall(conn, {"ok": True})
        elif op == "history_get":
            # blocks until EVERY process uploaded — the end-of-run barrier
            with self._hist_cv:
                self._hist_cv.wait_for(
                    lambda: len(self._histories) >= self.expected,
                    timeout=header.get("timeout", 600))
                if len(self._histories) < self.expected:
                    _sendall(conn, {"error": "history barrier timeout: "
                                    f"{sorted(self._histories)} of "
                                    f"{self.expected} processes uploaded"})
                    return
                merged = sorted(
                    (w for ws in self._histories.values() for w in ws),
                    key=lambda w: w[0])
            center, clock = self.ps.pull()
            _sendall(conn, {"windows": merged, "clock": clock},
                     self.codec.encode(center))
        else:
            _sendall(conn, {"error": f"unknown op {op!r}"})

    # -- direct (in-process) counterparts for process 0 -------------------
    def put_history(self, pid: int, windows: list) -> None:
        with self._hist_cv:
            self._histories[int(pid)] = [
                [int(c), float(s), steps] for c, s, steps in windows]
            self._hist_cv.notify_all()

    def get_history_blocking(self, timeout: float = 600):
        with self._hist_cv:
            ok = self._hist_cv.wait_for(
                lambda: len(self._histories) >= self.expected,
                timeout=timeout)
            if not ok:
                raise TimeoutError(
                    f"history barrier: {sorted(self._histories)} of "
                    f"{self.expected} processes uploaded")
            merged = sorted(
                (w for ws in self._histories.values() for w in ws),
                key=lambda w: w[0])
        center, clock = self.ps.pull()
        return merged, device_get_batched(center), clock


class RemoteParameterServer:
    """Client drop-in for the ParameterServer interface over the service.

    One connection per process; worker threads share it behind a lock, so
    a process's pulls/commits serialize on the wire (their windows still
    overlap in compute) — the same contention profile as the reference's
    per-executor socket. ``pull``/``commit`` return exactly what the local
    classes return, so HostAsyncRunner cannot tell the difference.
    """

    def __init__(self, address: str, like, timeout: float = 600.0,
                 token: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        self.codec = _TreeCodec(like)
        self.token = token
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def _roundtrip(self, header: dict, blobs=()) -> Tuple[dict, list]:
        op = header.get("op", "?")
        if self.token is not None:
            header = dict(header, token=self.token)
        t0 = time.perf_counter()
        with self._lock:
            _sendall(self._sock, header, blobs)
            resp, rblobs = _recv(self._sock)
        # rtt includes the wait for the shared connection: the contention
        # profile of the one-socket-per-process design is part of what a
        # STALENESS round wants to see
        telemetry.histogram("remote_ps.client.rtt_s",
                            op=op).record(time.perf_counter() - t0)
        telemetry.counter("remote_ps.client.bytes_sent").inc(
            sum(len(b) for b in blobs))
        telemetry.counter("remote_ps.client.bytes_received").inc(
            sum(len(b) for b in rblobs))
        if "error" in resp:
            raise RuntimeError(f"parameter service: {resp['error']}")
        return resp, rblobs

    def pull(self):
        resp, blobs = self._roundtrip({"op": "pull"})
        return self.codec.decode(blobs), resp["clock"]

    def commit(self, delta: Any, last_update: int = 0) -> int:
        resp, _ = self._roundtrip(
            {"op": "commit", "last_update": int(last_update)},
            self.codec.encode(delta))
        return resp["at_fold"]

    @property
    def num_updates(self) -> int:
        return self._roundtrip({"op": "clock"})[0]["clock"]

    def put_history(self, pid: int, windows: list) -> None:
        self._roundtrip({"op": "history_put", "pid": int(pid),
                         "windows": [[int(c), float(s), steps]
                                     for c, s, steps in windows]})

    def get_history(self, timeout: float = 600):
        resp, blobs = self._roundtrip({"op": "history_get",
                                       "timeout": timeout})
        return (resp["windows"], self.codec.decode(blobs), resp["clock"])

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    # reference lifecycle no-ops (parity with ParameterServer)
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


def share_service_address(port: Optional[int],
                          token: Optional[str] = None,
                          error: bool = False) -> Tuple[str, Optional[str]]:
    """Agree on the service address AND auth token across processes:
    process 0 broadcasts ``host:port|token`` through a tiny collective;
    everyone returns the same ``(address, token)`` pair.

    ``error=True`` (process 0 only) broadcasts a failure sentinel instead —
    the symmetric-agreement half of service construction (ADVICE r5): if
    process 0 could not bring the service up, its peers RAISE here instead
    of blocking in this broadcast until the collective timeout. Peers raise;
    process 0 returns a dummy so its own (real) exception propagates.
    """
    from jax.experimental import multihost_utils

    from distkeras_tpu.parallel.distributed import determine_host_address

    if jax.process_count() == 1:
        return f"127.0.0.1:{port}", token
    payload = np.zeros((192,), np.uint8)
    if jax.process_index() == 0:
        msg = ("!service construction failed on process 0" if error
               else f"{determine_host_address()}:{port}|{token or ''}")
        raw = msg.encode()
        if len(raw) > payload.size:
            raise ValueError(f"payload {raw!r} longer than "
                             f"{payload.size} bytes")
        payload[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(payload))
    msg = bytes(out[out != 0]).decode()
    if msg.startswith("!"):
        if jax.process_index() == 0:
            return "", None  # the original exception is already in flight
        raise RuntimeError(f"parameter service never came up: {msg[1:]}")
    addr, _, tok = msg.partition("|")
    return addr, (tok or None)
