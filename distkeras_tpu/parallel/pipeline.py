"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Not in the reference (dist-keras has no model parallelism of any kind —
SURVEY.md §2); built because a complete TPU framework needs all four axes:
dp (substrate / PjitTrainer), tp (parallel/tensor.py), sp
(parallel/sequence.py), and pp (this module).

Design — the JAX-native pipeline:
- The transformer's L decoder blocks are split into P stages of L/P layers;
  per-stage block params are STACKED with a leading [P, ...] axis and
  sharded over the ``stages`` mesh axis. Embedding/head params replicate.
- The forward pass is written as ONE ``lax.scan`` over M + P - 1 ticks
  inside ``shard_map``: each tick, stage 0 ingests the next microbatch,
  every stage applies its block stack, the last stage folds loss terms, and
  activations hop to the next stage via ``ppermute``. A device's idle ticks
  (pipeline bubble) compute on zeros — the cost model of GPipe.
- **Backward is free**: ``jax.grad`` differentiates through the scan and the
  ppermute; AD's transpose of a forward hop is exactly the reverse-schedule
  hop, and the transpose of replicated params is the cross-stage psum.
  Nobody hand-writes a 1F1B schedule.

Loss terms are summed with ``psum`` over stages, so the reported loss (and
therefore the gradients) equal the single-device computation on the same
global batch — asserted by tests.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distkeras_tpu.models.gpt import DecoderBlock
from distkeras_tpu.parallel import mesh as mesh_lib
from distkeras_tpu.utils.jax_compat import shard_map

STAGE_AXIS = "stages"


def make_pp_mesh(num_stages: int, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if num_stages > len(devices):
        raise ValueError(f"need {num_stages} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:num_stages]), (STAGE_AXIS,))


class PipelinedLM:
    """Causal LM split into P pipeline stages of L/P decoder blocks each.

    Not a flax module: a factory bundling (a) param init with the stacked
    stage layout and (b) the shard_map'd train/loss steps. Weights are
    interchangeable with a single-device model of the same config via the
    stacked layout (tested).
    """

    def __init__(self, vocab_size: int, max_len: int, num_layers: int,
                 num_heads: int, width: int, mlp_dim: int,
                 num_stages: int, dtype=jnp.float32):
        if num_layers % num_stages != 0:
            raise ValueError(f"num_layers {num_layers} must divide evenly "
                             f"into {num_stages} stages")
        self.vocab_size = vocab_size
        self.max_len = max_len
        self.num_layers = num_layers
        self.num_stages = num_stages
        self.layers_per_stage = num_layers // num_stages
        self.width = width
        self.dtype = dtype
        self.block = DecoderBlock(num_heads=num_heads, mlp_dim=mlp_dim,
                                  dtype=dtype, attention="full")

        class _Embed(nn.Module):
            vocab: int
            width: int
            max_len: int
            dtype: jnp.dtype

            @nn.compact
            def __call__(self, ids):
                x = nn.Embed(self.vocab, self.width, dtype=self.dtype,
                             name="tok_embed")(ids.astype(jnp.int32))
                pos = self.param("pos_embed", nn.initializers.normal(0.02),
                                 (self.max_len, self.width))
                return x + pos[:ids.shape[-1]].astype(self.dtype)

        class _Head(nn.Module):
            vocab: int
            dtype: jnp.dtype

            @nn.compact
            def __call__(self, x):
                x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
                return nn.Dense(self.vocab, dtype=jnp.float32,
                                name="lm_head")(x).astype(jnp.float32)

        self.embed = _Embed(vocab_size, width, max_len, dtype)
        self.head = _Head(vocab_size, dtype)

    # -- params ------------------------------------------------------------
    def init(self, rng, sample_ids) -> dict:
        """{"embed": ..., "blocks": [P, Lp, ...] stacked, "head": ...}"""
        r_embed, r_block, r_head = jax.random.split(rng, 3)
        embed = self.embed.init(r_embed, sample_ids)["params"]
        x = self.embed.apply({"params": embed}, sample_ids)

        def init_layer(key):
            return self.block.init(key, x)["params"]

        keys = jax.random.split(r_block, self.num_layers)
        stacked = jax.vmap(init_layer)(keys)  # [L, ...]
        blocks = jax.tree.map(
            lambda a: a.reshape((self.num_stages, self.layers_per_stage)
                                + a.shape[1:]), stacked)
        head = self.head.init(r_head, x)["params"]
        return {"embed": embed, "blocks": blocks, "head": head}

    def reference_apply(self, params, ids):
        """Single-device forward with the SAME stacked weights (oracle)."""
        x = self.embed.apply({"params": params["embed"]}, ids)
        flat = jax.tree.map(
            lambda a: a.reshape((self.num_layers,) + a.shape[2:]),
            params["blocks"])

        def body(x, layer_params):
            return self.block.apply({"params": layer_params}, x), None

        x, _ = jax.lax.scan(body, x, flat)
        return self.head.apply({"params": params["head"]}, x)

    # -- pipelined loss ----------------------------------------------------
    def _stage_apply(self, block_params, x):
        def body(x, layer_params):
            return self.block.apply({"params": layer_params}, x), None

        x, _ = jax.lax.scan(body, x, block_params)
        return x

    def build_train_step(self, tx: optax.GradientTransformation, mesh: Mesh,
                         num_microbatches: int):
        """(step_fn, place_params, place_batch); batch =
        {"input_ids": [B, T], "labels": [B, T]} with B divisible by
        num_microbatches; labels < 0 ignored."""
        M = num_microbatches
        stages = self.num_stages

        def pp_loss(params, ids_mb, labels_mb):
            # block params arrive [1, Lp, ...] on each device
            blocks = jax.tree.map(lambda a: a[0], params["blocks"])
            stage = jax.lax.axis_index(STAGE_AXIS)
            mb, t = ids_mb.shape[1], ids_mb.shape[2]
            zero_act = jnp.zeros((mb, t, self.width), self.dtype)

            def tick(carry, tick_i):
                buf, nll, hits, cnt = carry
                in_idx = jnp.clip(tick_i, 0, M - 1)
                x_in = jax.lax.cond(
                    stage == 0,
                    lambda: self.embed.apply(
                        {"params": params["embed"]},
                        ids_mb[in_idx]).astype(self.dtype),
                    lambda: buf)
                out = self._stage_apply(blocks, x_in)

                out_idx = jnp.clip(tick_i - (stages - 1), 0, M - 1)
                is_tail = jnp.logical_and(stage == stages - 1,
                                          tick_i >= stages - 1)

                def tail_loss():
                    logits = self.head.apply({"params": params["head"]}, out)
                    labels = labels_mb[out_idx]
                    valid = labels >= 0
                    safe = jnp.where(valid, labels, 0).astype(jnp.int32)
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    ll = jnp.take_along_axis(logp, safe[..., None],
                                             axis=-1)[..., 0]
                    l_nll = -jnp.sum(jnp.where(valid, ll, 0.0))
                    l_hits = jnp.sum(jnp.where(
                        valid, jnp.argmax(logits, -1) == safe, False)
                        .astype(jnp.float32))
                    l_cnt = jnp.sum(valid.astype(jnp.float32))
                    return l_nll, l_hits, l_cnt

                l_nll, l_hits, l_cnt = jax.lax.cond(
                    is_tail, tail_loss,
                    lambda: (jnp.float32(0), jnp.float32(0), jnp.float32(0)))
                perm = [(i, i + 1) for i in range(stages - 1)]
                buf = jax.lax.ppermute(out, STAGE_AXIS, perm)
                return (buf, nll + l_nll, hits + l_hits, cnt + l_cnt), None

            init = (zero_act, jnp.float32(0), jnp.float32(0), jnp.float32(0))
            (buf, nll, hits, cnt), _ = jax.lax.scan(
                tick, init, jnp.arange(M + stages - 1, dtype=jnp.int32))
            nll = jax.lax.psum(nll, STAGE_AXIS)
            hits = jax.lax.psum(hits, STAGE_AXIS)
            cnt = jnp.maximum(jax.lax.psum(cnt, STAGE_AXIS), 1.0)
            return nll / cnt, (nll, hits, cnt)

        # blocks spec: every leaf sharded on its leading (stage) axis
        def blocks_spec(blocks):
            return jax.tree.map(lambda _: P(STAGE_AXIS), blocks)

        def loss_shmapped(params, ids_mb, labels_mb):
            specs = {"embed": P(), "head": P(),
                     "blocks": blocks_spec(params["blocks"])}
            fn = shard_map(
                pp_loss, mesh=mesh,
                in_specs=(specs, P(), P()),
                out_specs=(P(), (P(), P(), P())),
                check_vma=False)
            return fn(params, ids_mb, labels_mb)

        def step(params, opt_state, batch):
            ids, labels = batch["input_ids"], batch["labels"]
            b = ids.shape[0]
            ids_mb = ids.reshape(M, b // M, ids.shape[1])
            labels_mb = labels.reshape(M, b // M, labels.shape[1])
            (loss, (nll, hits, cnt)), grads = jax.value_and_grad(
                loss_shmapped, has_aux=True)(params, ids_mb, labels_mb)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss, "accuracy": hits / cnt}

        step_fn = jax.jit(step, donate_argnums=(0, 1))

        def place_params(params):
            shardings = {
                "embed": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), params["embed"]),
                "head": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), params["head"]),
                "blocks": jax.tree.map(
                    lambda _: NamedSharding(mesh, P(STAGE_AXIS)),
                    params["blocks"]),
            }
            return mesh_lib.put_global(params, shardings)

        def place_batch(batch):
            return mesh_lib.put_global(batch, NamedSharding(mesh, P()))

        return step_fn, place_params, place_batch


class GenericPipeline:
    """GPipe over ARBITRARY stage modules — the stage-partitioning API.

    ``stages`` is any sequence of flax modules applied in order
    (``stages[k](x)``); they may be completely heterogeneous — different
    classes, widths, even activation SHAPES between stages. Stage k runs on
    mesh device k; activations hop stage-to-stage via ``ppermute`` through
    a single flat buffer padded to the largest inter-stage activation
    (static per-branch reshapes keep XLA happy); per-device stage dispatch
    is one ``lax.switch``. Backward is AD through the schedule, exactly as
    in :class:`PipelinedLM`.

    Trade-off vs the stacked homogeneous path (PipelinedLM): every stage's
    params are REPLICATED across the mesh (an SPMD program cannot place a
    pytree on only one device), so this buys arbitrary-model capability and
    compute/bubble behavior, not per-stage parameter memory scaling. Use
    the stacked layout when stages are homogeneous and params dominate.

    Loss: ``loss`` is a Keras-style name or callable ``(logits, labels) ->
    scalar`` applied to the LAST stage's output per microbatch.
    """

    def __init__(self, stages: Sequence[nn.Module], num_microbatches: int,
                 loss="categorical_crossentropy", dtype=jnp.float32):
        from distkeras_tpu.ops import losses as losses_lib

        if len(stages) < 2:
            raise ValueError("a pipeline needs >= 2 stages")
        self.stages = list(stages)
        self.num_stages = len(stages)
        self.M = int(num_microbatches)
        self.dtype = dtype
        self.loss_fn = losses_lib.get(loss) if isinstance(loss, str) else loss
        self._shapes: Optional[list] = None  # per-stage output shapes

    # -- params ------------------------------------------------------------
    def init(self, rng, sample_features) -> tuple:
        """Tuple of per-stage param trees; also records the static
        activation shapes for one microbatch of this shape."""
        keys = jax.random.split(rng, self.num_stages)
        params = []
        shapes = []
        x = jnp.asarray(sample_features, self.dtype)
        for k, (stage, key) in enumerate(zip(self.stages, keys)):
            p = stage.init(key, x)["params"]
            x = stage.apply({"params": p}, x)
            shapes.append(tuple(x.shape))
            params.append(p)
        self._shapes = shapes
        return tuple(params)

    def reference_apply(self, params, features):
        """Single-device sequential forward with the same params (oracle)."""
        x = jnp.asarray(features, self.dtype)
        for stage, p in zip(self.stages, params):
            x = stage.apply({"params": p}, x)
        return x

    # -- pipelined train step ----------------------------------------------
    def build_train_step(self, tx: optax.GradientTransformation, mesh: Mesh):
        """(step_fn, place_params, place_batch); batch =
        {"features": [B, ...], "labels": [B, ...]} with B divisible by
        num_microbatches. step_fn(params, opt_state, batch) ->
        (params, opt_state, {"loss"}).
        """
        if self._shapes is None:
            raise RuntimeError("call init() before build_train_step()")
        stages_n = self.num_stages
        M = self.M
        if mesh.shape[STAGE_AXIS] != stages_n:
            raise ValueError(
                f"mesh has {mesh.shape[STAGE_AXIS]} stage devices, "
                f"pipeline has {stages_n} stages")
        # hop buffer: outputs of stages 0..P-2 travel; pad to the largest
        hop_sizes = [int(np.prod(s)) for s in self._shapes[:-1]]
        buf_n = max(hop_sizes)
        shapes = self._shapes

        def pp_loss(params, feats_mb, labels_mb):
            stage = jax.lax.axis_index(STAGE_AXIS)

            def branch(k):
                def run(buf, feat_in, label):
                    if k == 0:
                        x = feat_in.astype(self.dtype)
                    else:
                        n_in = hop_sizes[k - 1]
                        x = buf[:n_in].reshape(shapes[k - 1])
                    out = self.stages[k].apply({"params": params[k]}, x)
                    if k == stages_n - 1:
                        l = self.loss_fn(out.astype(jnp.float32), label)
                        flat = jnp.zeros((buf_n,), self.dtype)
                    else:
                        l = jnp.float32(0)
                        flat = jnp.pad(
                            out.reshape(-1).astype(self.dtype),
                            (0, buf_n - hop_sizes[k]))
                    return flat, l
                return run

            branches = [branch(k) for k in range(stages_n)]

            def tick(carry, tick_i):
                buf, loss_sum, loss_cnt = carry
                in_idx = jnp.clip(tick_i, 0, M - 1)
                out_idx = jnp.clip(tick_i - (stages_n - 1), 0, M - 1)
                flat, l = jax.lax.switch(
                    stage, branches, buf, feats_mb[in_idx],
                    labels_mb[out_idx])
                # the tail stage only produces real losses once the first
                # microbatch has traversed the pipe
                live = jnp.logical_and(stage == stages_n - 1,
                                       tick_i >= stages_n - 1)
                loss_sum = loss_sum + jnp.where(live, l, 0.0)
                loss_cnt = loss_cnt + jnp.where(live, 1.0, 0.0)
                perm = [(i, i + 1) for i in range(stages_n - 1)]
                buf = jax.lax.ppermute(flat, STAGE_AXIS, perm)
                return (buf, loss_sum, loss_cnt), None

            init = (jnp.zeros((buf_n,), self.dtype), jnp.float32(0),
                    jnp.float32(0))
            (_, loss_sum, loss_cnt), _ = jax.lax.scan(
                tick, init, jnp.arange(M + stages_n - 1, dtype=jnp.int32))
            loss_sum = jax.lax.psum(loss_sum, STAGE_AXIS)
            loss_cnt = jnp.maximum(jax.lax.psum(loss_cnt, STAGE_AXIS), 1.0)
            return loss_sum / loss_cnt

        def loss_shmapped(params, feats_mb, labels_mb):
            fn = shard_map(
                pp_loss, mesh=mesh,
                in_specs=(tuple(jax.tree.map(lambda _: P(), p)
                                for p in params), P(), P()),
                out_specs=P(),
                check_vma=False)
            return fn(params, feats_mb, labels_mb)

        def step(params, opt_state, batch):
            feats, labels = batch["features"], batch["labels"]
            b = feats.shape[0]
            if b % M != 0:
                raise ValueError(f"batch {b} not divisible by "
                                 f"microbatches {M}")
            feats_mb = feats.reshape((M, b // M) + feats.shape[1:])
            labels_mb = labels.reshape((M, b // M) + labels.shape[1:])
            loss, grads = jax.value_and_grad(loss_shmapped)(
                params, feats_mb, labels_mb)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"loss": loss}

        step_fn = jax.jit(step, donate_argnums=(0, 1))

        def place_params(params):
            return mesh_lib.put_global(
                params, jax.tree.map(lambda _: NamedSharding(mesh, P()),
                                     params))

        def place_batch(batch):
            return mesh_lib.put_global(batch, NamedSharding(mesh, P()))

        return step_fn, place_params, place_batch
