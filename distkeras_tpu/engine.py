"""Core step engine: TrainState + jit-compiled update steps.

This is the TPU-native replacement for what the reference delegates to Keras:
``model.compile`` + ``train_on_batch`` inside each Spark executor
(``distkeras/workers.py`` — unverified, mount empty; see SURVEY.md). Instead
of an eager per-batch call into a TF1 session, the whole update step —
forward, backward, optimizer — is a single pure function traced once by XLA,
so it tiles onto the MXU and fuses elementwise work into the matmuls.

Design rules honored here:
- static shapes only; the data pipeline pads/drops ragged tails,
- no Python control flow inside the step,
- state is donated so XLA updates parameters in place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from distkeras_tpu.ops import losses as losses_lib
from distkeras_tpu import precision as precision_lib
from distkeras_tpu.utils.trees import global_norm

Batch = dict  # {"features": ..., "labels": ...} plus model-specific keys
ApplyFn = Callable[..., jax.Array]


@struct.dataclass
class TrainState:
    """Replicated training state: the analogue of one worker's compiled model.

    The parameter-server 'center variable' of the reference is a TrainState's
    ``params`` living replicated (or sharded) on device, not a pickled dict on
    a driver socket thread.
    """

    step: jax.Array
    params: Any
    opt_state: Any


def create_train_state(model, rng, sample_batch: Batch,
                       tx: optax.GradientTransformation) -> TrainState:
    """Initialize params + optimizer state from a sample batch (shapes only)."""
    x = sample_batch["features"]
    variables = model.init(rng, x, train=False)
    params = variables["params"]
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=tx.init(params))


def make_loss_fn(model, loss) -> Callable:
    """(params, batch, rngs) -> (scalar loss, logits). Resolves Keras-style
    loss names. Logits ride along as aux so metrics reuse the forward pass.

    The forward pass runs with ``mutable=["losses"]`` so auxiliary losses
    sown by modules (e.g. the Switch-MoE load-balance term, already scaled
    by the module's own weight) are folded into the objective — every
    trainer gets them for free."""
    loss_fn = losses_lib.get(loss)

    def compute(params, batch: Batch, rngs: Optional[dict] = None):
        kwargs = {"rngs": rngs} if rngs else {}
        logits, mutated = model.apply(
            {"params": params}, batch["features"], train=True,
            mutable=["losses"], **kwargs)
        total = loss_fn(logits, batch["labels"])
        for aux in jax.tree.leaves(mutated.get("losses", {})):
            total = total + jnp.sum(aux)
        return total, logits

    return compute


def compute_metric_terms(name: str, logits: jax.Array,
                         labels: jax.Array) -> tuple:
    """(numerator, denominator) f32 pair of one metric over one (micro)batch.

    The pair is SUMMABLE: adding the terms of k microbatches and finalizing
    (:func:`finalize_metric`) gives exactly the metric of the concatenated
    batch — the property gradient accumulation needs, which a mean of
    per-microbatch ratios does NOT have for masked accuracy (microbatches
    carry different valid-position counts).
    """
    if name in ("accuracy", "acc", "categorical_accuracy", "masked_accuracy"):
        pred = jnp.argmax(logits, axis=-1)
        if labels.ndim == logits.ndim - 1:  # integer labels
            valid = labels >= 0
            hit = jnp.where(valid, (pred == labels), False)
            return (jnp.sum(hit.astype(jnp.float32)),
                    jnp.sum(valid.astype(jnp.float32)))
        true = jnp.argmax(labels, axis=-1)
        return (jnp.sum((pred == true).astype(jnp.float32)),
                jnp.float32(pred.size))
    if name == "loss":  # already reported separately
        raise ValueError("'loss' is always recorded; don't list it in metrics")
    raise ValueError(f"Unknown metric {name!r}; supported: 'accuracy', "
                     "'masked_accuracy'")


def finalize_metric(terms: tuple) -> jax.Array:
    """num/den of accumulated metric terms (den clamped: an all-masked
    batch reports 0, not NaN)."""
    num, den = terms
    return num / jnp.maximum(den, 1.0)


def compute_metric(name: str, logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Keras-style training metrics over one batch.

    Integer-label accuracy ignores positions with label < 0 (the masked_lm
    ignore convention) so 'accuracy' is meaningful for MLM training too;
    'masked_accuracy' is an explicit alias.
    """
    return finalize_metric(compute_metric_terms(name, logits, labels))


def make_train_step(model, loss, tx: optax.GradientTransformation,
                    with_metrics: bool = True,
                    metrics: tuple = (),
                    dropout_seed: int = 0,
                    accum_steps: int = 1,
                    precision=None) -> Callable:
    """Build the jitted single-replica train step.

    Returns ``step(state, batch) -> (state, metrics)`` where metrics is a dict
    of scalar device arrays (loss, grad_norm, requested metrics). Already
    jitted with donated state. A per-step dropout rng is derived by folding
    the step counter into ``dropout_seed``, so stochastic layers just work.

    ``accum_steps=k`` splits each batch into k microbatches scanned
    sequentially, summing gradients in f32 and applying the optimizer ONCE —
    the memory-for-compute trade (NUMERICS.md: equals the full-batch step on
    the mean-loss objective). The batch's leading dim must be divisible by k.
    """
    one_step = _make_step_body(model, loss, tx, with_metrics, metrics,
                               dropout_seed, accum_steps,
                               precision=precision)
    return jax.jit(one_step, donate_argnums=(0,))


def _split_microbatches(batch: Batch, k: int) -> Batch:
    """[k*m, ...] batch leaves -> [k, m, ...]; loud error on a ragged split."""

    def split(x):
        b = x.shape[0]
        if b % k != 0:
            raise ValueError(
                f"accum_steps={k} must divide the per-step batch "
                f"(got a leaf with leading dim {b})")
        return x.reshape((k, b // k) + x.shape[1:])

    return jax.tree.map(split, batch)


def make_accum_grad_fn(model, loss, accum_steps: int,
                       metric_names: tuple = (),
                       precision=None) -> Callable:
    """Gradient-accumulation counterpart of :func:`make_grad_fn`, same
    contract: ``(params, batch, rngs) -> ((loss, aux), grads)`` — so every
    strategy's ``local_step`` composes with it unchanged.

    The [k*m, ...] batch is scanned as k microbatches of m rows; per-
    microbatch grads are summed in f32 and divided by k, which equals the
    full-batch mean-loss gradient exactly (equal microbatch sizes make the
    mean of means the overall mean). Peak activation memory is that of ONE
    microbatch. ``aux`` is ``{metric: (num, den)}`` f32 term pairs (see
    :func:`compute_metric_terms`) rather than logits — re-materializing
    full-batch logits (for MLM, [batch, seq, vocab]) would hand back the
    memory the microbatching just saved.

    The dropout key is folded per microbatch index, so stochastic layers
    see k independent masks (they cannot see the one full-batch mask — the
    parity guarantee is for the deterministic objective; see NUMERICS.md).

    Aux losses sown from batch statistics (e.g. the Switch-MoE load-balance
    term) are computed per microbatch and averaged — a batch-statistics
    dependence analogous to BatchNorm's, documented rather than hidden.
    """
    compute_loss = make_loss_fn(model, loss)
    k = int(accum_steps)
    if k < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    metric_names = tuple(metric_names)
    policy, scaling = _loss_scaling(precision)

    def grad_fn(params, batch: Batch, rngs: Optional[dict] = None,
                loss_scale=None):
        micro = _split_microbatches(batch, k)
        if scaling is None:
            scale = None
        else:
            scale = jnp.float32(policy.loss_scale) if loss_scale is None \
                else loss_scale

        def body(acc, xs):
            batch_i, i = xs
            rngs_i = None if rngs is None else {
                name: jax.random.fold_in(key, i)
                for name, key in rngs.items()}
            if scale is None:
                (l, logits), g = jax.value_and_grad(
                    compute_loss, has_aux=True)(params, batch_i, rngs_i)
            else:
                # per-microbatch loss scaling; the f32 SUM below is of the
                # scaled grads — unscaled once after the scan (exact for
                # power-of-two scales)
                def scaled(p, b, r):
                    l, logits = compute_loss(p, b, r)
                    return scaling[0](l, scale), (l, logits)

                (_, (l, logits)), g = jax.value_and_grad(
                    scaled, has_aux=True)(params, batch_i, rngs_i)
            terms = {name: compute_metric_terms(name, logits,
                                                batch_i["labels"])
                     for name in metric_names}
            loss_acc, terms_acc, grads_acc = acc
            grads_acc = jax.tree.map(
                lambda a, gi: a + gi.astype(jnp.float32), grads_acc, g)
            terms_acc = jax.tree.map(lambda a, t: a + t, terms_acc, terms)
            return (loss_acc + l.astype(jnp.float32), terms_acc,
                    grads_acc), None

        zeros_like_f32 = lambda t: jax.tree.map(
            lambda x: jnp.zeros(jnp.shape(x), jnp.float32), t)
        init = (jnp.float32(0.0),
                {name: (jnp.float32(0.0), jnp.float32(0.0))
                 for name in metric_names},
                zeros_like_f32(params))
        (loss_sum, terms, grad_sum), _ = jax.lax.scan(
            body, init, (micro, jnp.arange(k, dtype=jnp.int32)))
        if scale is not None:
            grad_sum = scaling[1](grad_sum, scale)
        grads = jax.tree.map(
            lambda g, p: (g / k).astype(jnp.asarray(p).dtype),
            grad_sum, params)
        return (loss_sum / k, terms), grads

    return grad_fn


def _make_step_body(model, loss, tx: optax.GradientTransformation,
                    with_grad_norm: bool, metrics: tuple,
                    dropout_seed: int, accum_steps: int = 1,
                    precision=None) -> Callable:
    """The ONE unjitted step body shared by :func:`make_train_step` and
    :func:`make_epoch_fn` — keeping them numerically identical by
    construction, not by hand-synced copies. ``accum_steps > 1`` swaps the
    full-batch grad for the scanned microbatch accumulation
    (:func:`make_accum_grad_fn`); the optimizer still applies once per step,
    so ``state.step`` counts OPTIMIZER steps either way.

    ``precision=`` threads a loss-scaling policy into the grad fn; when
    ``tx`` is ``precision.overflow_guard``-wrapped, the LIVE loss scale is
    read out of the optimizer state (``current_scale``) and fed forward —
    the dynamic skip-and-rescale loop closes here."""
    metric_names = tuple(metrics)
    base_key = jax.random.key(dropout_seed)
    accum_steps = int(accum_steps)
    if accum_steps > 1:
        accum_grad = make_accum_grad_fn(model, loss, accum_steps,
                                        metric_names, precision=precision)

        def one_step(state: TrainState, batch: Batch):
            rngs = {"dropout": jax.random.fold_in(base_key, state.step)}
            scale = precision_lib.current_scale(state.opt_state)
            (loss_val, terms), grads = accum_grad(state.params, batch, rngs,
                                                  loss_scale=scale)
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)
            params = optax.apply_updates(state.params, updates)
            out = {"loss": loss_val}
            if with_grad_norm:
                out["grad_norm"] = global_norm(grads)
            for name in metric_names:
                out[name] = finalize_metric(terms[name])
            return TrainState(step=state.step + 1, params=params,
                              opt_state=opt_state), out

        return one_step
    grad_fn = make_grad_fn(model, loss, precision=precision)

    def one_step(state: TrainState, batch: Batch):
        rngs = {"dropout": jax.random.fold_in(base_key, state.step)}
        scale = precision_lib.current_scale(state.opt_state)
        (loss_val, logits), grads = grad_fn(state.params, batch, rngs,
                                            loss_scale=scale)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        out = {"loss": loss_val}
        if with_grad_norm:
            out["grad_norm"] = global_norm(grads)
        for name in metric_names:
            out[name] = compute_metric(name, logits, batch["labels"])
        return TrainState(step=state.step + 1, params=params,
                          opt_state=opt_state), out

    return one_step


def make_epoch_fn(model, loss, tx: optax.GradientTransformation,
                  metrics: tuple = (), dropout_seed: int = 0,
                  accum_steps: int = 1, precision=None) -> Callable:
    """Scanned single-replica epoch: the whole staged chunk in ONE device
    call.

    ``epoch(state, data) -> (state, metrics)`` where ``data`` leaves are
    [steps, batch, ...] and metrics values are [steps] arrays. Numerics are
    identical to looping :func:`make_train_step` over the same batches by
    construction — both scan/loop the same :func:`_make_step_body` — but a
    whole epoch costs one dispatch instead of one per step (which on
    tunneled backends is ~100x the difference). ``accum_steps=k`` microbatches
    each step (see :func:`make_train_step`).
    """
    one_step = _make_step_body(model, loss, tx, True, metrics, dropout_seed,
                               accum_steps, precision=precision)

    def epoch(state: TrainState, data: Batch):
        return jax.lax.scan(one_step, state, data)

    return jax.jit(epoch, donate_argnums=(0,))


def _loss_scaling(precision):
    """(policy, (pre, post)) when the policy actively loss-scales, else
    (policy, None). f32/bf16 default to scale 1.0 — no scaling code at
    all, so those paths stay bitwise-identical to precision=None."""
    policy = precision_lib.get_policy(precision)
    if policy is None or policy.loss_scale == 1.0:
        return policy, None
    return policy, precision_lib.scale_grads_fn(policy)


def make_grad_fn(model, loss, precision=None) -> Callable:
    """(params, batch) -> ((loss, logits), grads); building block for the
    parallel substrate where the optimizer application happens per-strategy.

    ``precision=`` (DESIGN.md §11): a quantizing policy scales the loss by
    the policy's loss scale before ``grad`` and unscales the gradients in
    f32 after (exact for the power-of-two scales used), guarding low-
    precision backward passes against underflow-to-zero gradient noise.
    The reported loss is the UNSCALED one. The optional ``loss_scale``
    call kwarg lets a step body feed the LIVE scale from an
    ``overflow_guard``-wrapped optimizer state; strategies that call with
    three arguments get the policy's static scale — documented asymmetry.
    """
    compute_loss = make_loss_fn(model, loss)
    policy, scaling = _loss_scaling(precision)
    if scaling is None:
        def grad_fn(params, batch: Batch, rngs: Optional[dict] = None,
                    loss_scale=None):
            return jax.value_and_grad(compute_loss, has_aux=True)(
                params, batch, rngs)

        return grad_fn
    pre, post = scaling

    def grad_fn(params, batch: Batch, rngs: Optional[dict] = None,
                loss_scale=None):
        scale = jnp.float32(policy.loss_scale) if loss_scale is None \
            else loss_scale

        def scaled(p, b, r):
            l, logits = compute_loss(p, b, r)
            return pre(l, scale), (l, logits)

        (_, (loss_val, logits)), grads = jax.value_and_grad(
            scaled, has_aux=True)(params, batch, rngs)
        return (loss_val, logits), post(grads, scale)

    return grad_fn


def make_eval_step(model) -> Callable:
    """Jitted forward pass: (params, features) -> logits."""

    def forward(params, x):
        return model.apply({"params": params}, x, train=False)

    return jax.jit(forward)
