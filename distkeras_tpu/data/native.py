"""ctypes binding for the native batch assembler (native_src/batcher.cc).

Compiled on first use with g++, cached next to the source (or under
``~/.cache/distkeras_tpu`` when the install dir is read-only, e.g. a system
site-packages); every entry point falls back to NumPy when the toolchain or
the .so is unavailable, so the framework never hard-depends on the native
path — it is a throughput optimization for the host side of the input
pipeline.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native_src", "batcher.cc")
_CACHE_SO = os.path.join(
    os.environ.get("XDG_CACHE_HOME",
                   os.path.join(os.path.expanduser("~"), ".cache")),
    "distkeras_tpu", "libdkbatch.so")


def _build() -> Optional[str]:
    if not os.path.exists(_SRC):
        return None
    # Prefer caching next to the source (source checkouts); fall back to the
    # user cache dir when the install location is read-only (system installs).
    for so in (os.path.join(os.path.dirname(_SRC), "libdkbatch.so"),
               _CACHE_SO):
        try:
            if os.path.exists(so) and (os.path.getmtime(so) >=
                                       os.path.getmtime(_SRC)):
                return so
            os.makedirs(os.path.dirname(so), exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", so, _SRC,
                 "-lpthread"],
                check=True, capture_output=True, timeout=120)
            return so
        except Exception:
            continue
    return None


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.dk_gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int32]
            lib.dk_gather_rows.restype = None
            lib.dk_permutation.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
            lib.dk_permutation.restype = None
            _LIB = lib
        except OSError:
            _LIB = None
        return _LIB


def available() -> bool:
    return _lib() is not None


def gather_rows(src: np.ndarray, idx: np.ndarray,
                num_threads: int = 0) -> np.ndarray:
    """out[i] = src[idx[i]] — native threaded memcpy gather with numpy
    fallback. src may have any row shape; idx is int64 [n]."""
    lib = _lib()
    idx = np.ascontiguousarray(idx, np.int64)
    src = np.asarray(src)
    if lib is None or src.dtype.hasobject:
        # object rows are PyObject pointers — memcpy without incref corrupts
        # the interpreter; those columns stay on the numpy path
        return src[idx]
    if idx.size and (idx.min() < 0 or idx.max() >= len(src)):
        raise IndexError(  # match the numpy fallback, don't memcpy OOB
            f"gather indices out of range [0, {len(src)}): "
            f"[{idx.min()}, {idx.max()}]")
    src = np.ascontiguousarray(src)
    n = len(idx)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((n,) + src.shape[1:], src.dtype)
    if num_threads <= 0:
        num_threads = min(8, os.cpu_count() or 1)
    lib.dk_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        idx.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n), ctypes.c_int64(row_bytes),
        ctypes.c_int32(num_threads))
    return out


def permutation(n: int, seed: int) -> np.ndarray:
    """Deterministic Fisher-Yates permutation of [0, n); native xoshiro256**
    with numpy fallback (NOTE: the two paths draw different sequences — both
    deterministic by seed, but not bit-identical to each other)."""
    lib = _lib()
    if lib is None:
        return np.random.default_rng(seed).permutation(n).astype(np.int64)
    out = np.empty(n, np.int64)
    lib.dk_permutation(out.ctypes.data_as(ctypes.c_void_p),
                       ctypes.c_int64(n), ctypes.c_uint64(seed & (2**64 - 1)))
    return out
