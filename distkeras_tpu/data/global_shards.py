"""Cross-host data mixing for the host-sharded input contract.

Reference parity gap this closes (VERDICT r4 weak #3): dist-keras's
``utils.shuffle(df)`` re-dealt rows to Spark executors on EVERY call, so no
executor was permanently married to a data subset. The host-sharded
contract here ("each process's dataset holds only its own workers' rows")
is pod-scale-honest but STATIC — a host would see the same subset every
epoch, permanently correlating each EASGD replica's data distribution with
its host.

:class:`GlobalShards` restores the reference's global semantics at zero
RAM cost: the dataset is a pool of equal-sized shard FILES visible to
every host (shared filesystem / object store — the same assumption Spark
made); each epoch, a seed-derived permutation re-deals shard files to
hosts, and a host opens ONLY its epoch's files (lazy mmap — re-pointing
hosts at different files moves no bytes). Within-host order can further be
shuffled by the trainer's ``shuffle=True`` (lazy ``PermutedColumn``).

Every host computes the same permutation from (seed, epoch) with no
communication — the same determinism trick as the substrate's rotation.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from distkeras_tpu.data.dataset import Dataset, ShardedColumn
from distkeras_tpu.utils import rng


class ShardingError(ValueError):
    """A shard pool that cannot satisfy the legacy equal-shard contract
    (unequal row counts, mismatched shard counts, or a shard count that
    does not divide the process count). Subclasses :class:`ValueError` so
    pre-existing broad handlers keep working; the message always names the
    offending counts. The streaming data service
    (:mod:`distkeras_tpu.data.service`) has no such constraint — it is the
    intended escape when this fires."""


class GlobalShards:
    """An epoch-seeded assignment of shard files to hosts.

    ``columns`` maps each column name to the FULL ordered list of its shard
    file paths (``.npy``); every host passes the same lists. All shards of
    a column must hold the same row count, and all columns the same shard
    count (so any shard index selects consistent rows across columns and
    every host stages equal row counts — the host-sharded contract's
    static-shape requirement).

    Pass the object wherever a host-sharded trainer takes a dataset::

        gs = GlobalShards({"features": feat_paths, "label": label_paths})
        ADAG(model, ..., data_layout="host_sharded").train(gs)

    Epoch e on process p sees ``epoch_dataset(e)`` — the shards at
    ``permutation(seed, e)[p * S/P : (p+1) * S/P]``, presented as one lazy
    Dataset. The union over processes is the whole pool (a permutation), so
    the global per-epoch multiset of rows is preserved while each host's
    subset changes every epoch.

    **Legacy equal-shard constraint (superseded).** This path requires
    equal-sized shard files, a shard count divisible by the process count,
    and a filesystem every host can see — Spark's assumptions from the
    dist-keras lineage, enforced here as typed :class:`ShardingError`\\ s.
    The streaming data service (:mod:`distkeras_tpu.data.service`,
    DESIGN.md §20) supersedes all three: a :class:`~distkeras_tpu.data.
    service.DataCoordinator` leases unequal row ranges to however many
    workers are alive, and :meth:`streaming_dataset` is the bridge — the
    whole pool as one lazy Dataset for the coordinator to serve.
    """

    def __init__(self, columns: Dict[str, Sequence[Union[str, bytes]]],
                 seed: int = 0, mmap: bool = True):
        if not columns:
            raise ValueError("GlobalShards needs at least one column")
        counts = {c: len(ps) for c, ps in columns.items()}
        if len(set(counts.values())) != 1:
            raise ShardingError(
                f"Every column needs the SAME shard count (shard i of each "
                f"column holds the same rows); got {counts}")
        self.num_shards = next(iter(counts.values()))
        if self.num_shards == 0:
            raise ValueError("GlobalShards needs at least one shard file")
        self.seed = int(seed)
        self._mmap = bool(mmap)
        self._paths: Dict[str, List[str]] = {
            c: [str(p) for p in ps] for c, ps in columns.items()}
        # Validate row counts from the npy HEADERS alone: no memmaps (and
        # no file descriptors) are held open here — a pool of thousands of
        # shard files must not exhaust the fd limit at construction; files
        # are opened lazily in epoch_dataset, only the shards assigned to
        # this host this epoch.
        sizes = {self._npy_rows(p)
                 for ps in self._paths.values() for p in ps}
        if len(sizes) != 1:
            raise ShardingError(
                f"All shard files must hold the SAME row count (hosts must "
                f"stage equal rows under the static-shape contract); got "
                f"sizes {sorted(sizes)} — unequal shards stream fine "
                f"through data.service.DataCoordinator")
        self.rows_per_shard = sizes.pop()

    @staticmethod
    def _npy_rows(path: str) -> int:
        """Leading-axis length read from the .npy header (fd closed on
        return — nothing stays open)."""
        with open(path, "rb") as f:
            version = np.lib.format.read_magic(f)
            if version >= (2, 0):
                shape, _, _ = np.lib.format.read_array_header_2_0(f)
            else:
                shape, _, _ = np.lib.format.read_array_header_1_0(f)
        if not shape:
            raise ValueError(f"{path!r} holds a 0-d array, not rows")
        return int(shape[0])

    @property
    def columns(self) -> List[str]:
        return list(self._paths)

    def __len__(self) -> int:
        """Total rows in the pool (all shards)."""
        return self.num_shards * self.rows_per_shard

    def epoch_assignment(self, epoch: int,
                         process_count: Optional[int] = None) -> List[List[int]]:
        """Per-process shard-index lists for one epoch — a contiguous split
        of the (seed, epoch)-permuted pool. Deterministic and
        communication-free: every host computes the same answer."""
        import jax

        p = process_count if process_count is not None else \
            jax.process_count()
        if self.num_shards % p:
            raise ShardingError(
                f"{self.num_shards} shard files do not split evenly over "
                f"{p} processes (remainder {self.num_shards % p}); provide "
                f"a multiple (equal host row counts are the host-sharded "
                f"contract), or stream the pool through "
                f"data.service.DataCoordinator, which has no divisibility "
                f"constraint")
        perm = rng.permutation(self.seed * 1_000_003 + epoch,
                               self.num_shards)
        per = self.num_shards // p
        return [list(map(int, perm[i * per:(i + 1) * per]))
                for i in range(p)]

    def epoch_dataset(self, epoch: int,
                      process_index: Optional[int] = None,
                      process_count: Optional[int] = None) -> Dataset:
        """This process's lazy Dataset for one epoch (no bytes read)."""
        import jax

        pi = process_index if process_index is not None else \
            jax.process_index()
        idxs = self.epoch_assignment(epoch, process_count)[pi]
        mode = "r" if self._mmap else None
        out = {}
        for c, paths in self._paths.items():
            chosen = [np.load(paths[i], mmap_mode=mode) for i in idxs]
            out[c] = chosen[0] if len(chosen) == 1 else ShardedColumn(chosen)
        return Dataset(out)

    def streaming_dataset(self) -> Dataset:
        """The WHOLE pool as one lazy Dataset — the bridge to the
        streaming data service (DESIGN.md §20)::

            coord = DataCoordinator(dataset=gs.streaming_dataset(), ...)

        Every shard becomes part of a lazy :class:`ShardedColumn` (mmap —
        no bytes read here); the coordinator reads only the row ranges
        workers actually lease, so only IT needs to see the files. No
        divisibility or equal-host-rows constraint applies: range
        permutation replaces shard permutation, and epoch/cursor state
        lives in the coordinator."""
        mode = "r" if self._mmap else None
        out = {}
        for c, paths in self._paths.items():
            parts = [np.load(p, mmap_mode=mode) for p in paths]
            out[c] = parts[0] if len(parts) == 1 else ShardedColumn(parts)
        return Dataset(out)
