"""Fault-tolerant streaming data service (DESIGN.md §20).

The legacy path (:class:`~distkeras_tpu.data.global_shards.GlobalShards`)
assumes equal-sized shard files, divisible counts, and a filesystem every
host can see — Spark's assumption from the dist-keras lineage, not a TPU
pod's. This module replaces that crutch with a **coordinator-leased range
protocol** on the exact remote_ps wire framing
(``[u32 header_len][JSON header][blobs...]`` + shared-token auth):

- The :class:`DataCoordinator` cuts the global row space ``[0, total_rows)``
  into fixed-size ranges (the LAST range is smaller — unequal shards are
  native, no divisibility constraint) and serves them to workers in a
  **deterministic, seeded, per-epoch permuted order**. A range's position
  in that permuted order is its ``stream_pos``: the global-stream order key
  that is independent of which worker ends up serving it, so resharding
  (1→N→M workers) never reorders the global stream.
- Workers hold **leases** (``health/membership.py`` — the same machinery
  as the elastic PS fleet). Every ``data_lease``/``data_ack`` renews; a
  worker that stops calling (killed, preempted, partitioned) lapses, and
  the lazy sweep re-queues its unacknowledged ranges for the survivors —
  the re-lease path the chaos acceptance test drives.
- **Exactly-once range retirement**: acks carry ``(cid, seq)`` exactly
  like PS commits; a retried ack (applied server-side, reply lost) replays
  the cached reply instead of double-retiring, and retirement itself is
  idempotent. The honest loss window is stated in DESIGN.md §20: a worker
  that *lands* a range's batches but dies before acking causes that range
  to replay on a survivor — the service guarantees each range is RETIRED
  exactly once; landing-side dedup (batch ids are deterministic functions
  of ``(epoch, row_start)``) closes the remaining window when the consumer
  needs it closed.
- The **shuffle cursor** ``[epoch, watermark]`` (watermark = length of the
  contiguous retired prefix of the permuted order) is a fixed-shape int64
  array that rides the Orbax ``carries`` composite; restoring it on a
  fresh coordinator resumes the stream **bitwise-deterministically** —
  the remaining stream is exactly ``perm[watermark:]`` of the same seeded
  permutation, whatever the crash timing was.
- **Streaming admission**: when the coordinator is constructed with a
  (lazily file-backed) :class:`~distkeras_tpu.data.dataset.Dataset`, the
  ``data_fetch`` op serves row ranges as npy blobs, so worker hosts never
  need the files or the RAM for the whole epoch — datasets larger than
  any one worker host become feedable.

Chaos sites (``utils/fault.py``): ``data.lease`` meters the server-side
dispatch (delay / reset / kill — the torn-coordinator drill) and
``data.fetch`` the client request egress (drop / delay / reset /
reset_after_send — the ack-dedup drill), mirroring ``remote_ps.send`` /
``remote_ps.server.handle``.
"""

from __future__ import annotations

import io
import os
import socket
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu import comms, telemetry
from distkeras_tpu.health import recorder as flight_recorder
from distkeras_tpu.health.endpoints import HEALTH_OPS, handle_health_op
from distkeras_tpu.health.membership import DEFAULT_LEASE_S, Membership
from distkeras_tpu.parallel.remote_ps import (check_token, recv_message,
                                              send_message)
from distkeras_tpu.utils import fault, rng

_sendall = send_message
_recv = recv_message


class DataServiceUnavailable(RuntimeError):
    """The data coordinator could not be reached within the retry budget —
    the typed signal (mirroring ``PSUnavailable``) streaming consumers key
    on instead of crashing on a bare socket error."""


def _encode_columns(cols: Dict[str, np.ndarray]) -> Tuple[list, list]:
    """(names, blobs): each column as one self-describing .npy blob
    (dtype + shape travel in the npy header, so heterogeneous columns
    round-trip without a side-channel schema)."""
    names, blobs = [], []
    for name, arr in cols.items():
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
        names.append(name)
        blobs.append(buf.getvalue())
    return names, blobs


def _decode_columns(names: Sequence[str],
                    blobs: Sequence[bytes]) -> Dict[str, np.ndarray]:
    return {name: np.load(io.BytesIO(blob), allow_pickle=False)
            for name, blob in zip(names, blobs)}


class DataCoordinator:
    """Socket front-end leasing permuted row ranges to streaming workers.

    ``total_rows`` may be given directly (workers hold the data and only
    need the *order*: local-slice mode) or implied by ``dataset=`` (the
    coordinator additionally serves the bytes via ``data_fetch`` —
    streaming admission). ``range_size`` is in rows; the last range keeps
    the remainder, so any ``(total_rows, range_size, worker count)``
    combination is legal — the typed :class:`~distkeras_tpu.data.
    global_shards.ShardingError` constraint of the legacy path does not
    exist here.

    The epoch stream is ``permutation(seed * 1_000_003 + epoch,
    num_ranges)`` (the GlobalShards seeding idiom, so the two paths are
    comparable): position ``p`` of the stream is range
    ``perm[p]``. Leases hand out positions in ascending stream order,
    re-queued (lapsed) positions first — deterministic given the op
    sequence. The durable cursor is ``[epoch, watermark]``; see the module
    docstring for its exactness contract.

    Thread-safe: one handler thread per connection mutates the ledger
    under one lock; no blocking call runs under it.
    """

    #: bounded per-client replay window for (cid, seq) lease/ack dedup —
    #: same rationale and bound as the PS commit dedup cache.
    DEDUP_CACHE = 128

    def __init__(self, total_rows: Optional[int] = None,
                 range_size: int = 1024,
                 seed: int = 0, num_epochs: int = 1,
                 dataset=None,
                 host: str = "0.0.0.0", port: int = 0,
                 token: Optional[str] = None,
                 lease_s: float = DEFAULT_LEASE_S,
                 time_fn: Callable[[], float] = time.time):
        if dataset is not None:
            n = len(dataset)
            if total_rows is not None and int(total_rows) != n:
                raise ValueError(
                    f"total_rows={total_rows} disagrees with the dataset's "
                    f"{n} rows; pass one or the other")
            total_rows = n
        if total_rows is None:
            raise ValueError("DataCoordinator needs total_rows= or dataset=")
        if total_rows <= 0:
            raise ValueError(f"total_rows must be > 0, got {total_rows}")
        if range_size <= 0:
            raise ValueError(f"range_size must be > 0, got {range_size}")
        if num_epochs <= 0:
            raise ValueError(f"num_epochs must be > 0, got {num_epochs}")
        self.total_rows = int(total_rows)
        self.range_size = int(range_size)
        self.num_ranges = -(-self.total_rows // self.range_size)
        self.seed = int(seed)
        self.num_epochs = int(num_epochs)
        self.dataset = dataset
        self.token = token
        self.membership = Membership(lease_s=lease_s, time_fn=time_fn)
        self._lock = threading.Lock()
        # -- epoch ledger (all under self._lock) ---------------------------
        self._epoch = 0
        self._perm = self._epoch_perm(0)
        self._next_pos = 0            # next never-dispatched stream position
        self._pending: List[int] = []  # re-queued positions, kept sorted
        self._outstanding: Dict[int, int] = {}      # pos -> worker
        self._worker_pos: Dict[int, set] = {}       # worker -> {pos}
        self._retired = np.zeros(self.num_ranges, bool)  # by stream pos
        self._watermark = 0
        self._releases = 0
        self._exhausted = self.num_epochs == 0
        self._dedup: dict = {}  # cid -> OrderedDict(seq -> reply header)
        self._dedup_lock = threading.Lock()
        # -- socket plumbing (the remote_ps service shape) -----------------
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self.address = f"127.0.0.1:{self.port}"
        self._running = False
        self._threads: list = []
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        telemetry.gauge("data.service.ranges").set(self.num_ranges)
        self._publish_gauges_locked()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed by stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def kill(self, reason: str = "chaos") -> None:
        """Simulate coordinator PROCESS DEATH (the chaos ``kill`` action):
        the listener and every live connection die instantly; in-flight
        requests get no reply. The torn-restart drill then constructs a
        FRESH coordinator and :meth:`restore_cursor`\\ s the checkpointed
        cursor — the remaining stream must be bitwise-identical to the
        uninterrupted run's suffix."""
        if not self._running:
            return
        telemetry.record_event("data_service", transition="killed",
                               reason=reason, epoch=int(self._epoch),
                               watermark=int(self._watermark))
        self.stop()
        with self._conn_lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        flight_recorder.auto_dump("data_coordinator_killed")

    def __enter__(self) -> "DataCoordinator":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- deterministic shuffle state --------------------------------------
    def _epoch_perm(self, epoch: int) -> np.ndarray:
        # the GlobalShards seeding idiom: every party (and every restart)
        # derives the same permutation from (seed, epoch) alone
        return rng.permutation(self.seed * 1_000_003 + epoch,
                               self.num_ranges)

    def _range_bounds(self, range_idx: int) -> Tuple[int, int]:
        start = range_idx * self.range_size
        return start, min(start + self.range_size, self.total_rows)

    def epoch_stream(self, epoch: int) -> List[Tuple[int, int, int]]:
        """The canonical global stream for one epoch:
        ``[(stream_pos, row_start, row_stop), ...]`` in stream order. Pure
        and communication-free — tests and consumers use it as the
        reference order that leasing (under any worker count or churn)
        must reproduce."""
        perm = self._epoch_perm(epoch)
        return [(p, *self._range_bounds(int(perm[p])))
                for p in range(self.num_ranges)]

    def cursor_carry(self) -> np.ndarray:
        """The durable shuffle cursor as a fixed-shape int64 array
        ``[epoch, watermark]`` — the leaf a trainer folds into its Orbax
        ``carries`` composite (DESIGN.md §20)."""
        with self._lock:
            if self._exhausted:
                return np.array([self.num_epochs, self.num_ranges],
                                np.int64)
            return np.array([self._epoch, self._watermark], np.int64)

    def restore_cursor(self, carry) -> None:
        """Resume from a :meth:`cursor_carry` snapshot: positions before
        the watermark are retired, everything after re-dispatches in the
        seeded permutation's order. Ranges consumed-but-unacked at crash
        time replay (the honest at-least-once window across coordinator
        crashes, DESIGN.md §20); the ORDER of the remaining stream is
        bitwise-deterministic."""
        arr = np.asarray(carry, np.int64).reshape(-1)
        if arr.size != 2:
            raise ValueError(
                f"cursor carry must be [epoch, watermark], got {arr!r}")
        epoch, watermark = int(arr[0]), int(arr[1])
        if not 0 <= watermark <= self.num_ranges:
            raise ValueError(
                f"watermark {watermark} outside [0, {self.num_ranges}]")
        with self._lock:
            if epoch >= self.num_epochs:
                self._epoch = self.num_epochs
                self._exhausted = True
            else:
                self._epoch = epoch
                self._exhausted = False
                self._perm = self._epoch_perm(epoch)
            self._pending = []
            self._outstanding = {}
            self._worker_pos = {}
            self._retired = np.zeros(self.num_ranges, bool)
            self._retired[:watermark] = True
            self._watermark = watermark
            self._next_pos = watermark
            self._publish_gauges_locked()
        telemetry.record_event("data_service", transition="restored",
                               epoch=epoch, watermark=watermark)

    # -- ledger (callers hold self._lock) ----------------------------------
    def _publish_gauges_locked(self) -> None:
        telemetry.gauge("data.service.cursor").set(self._watermark)
        telemetry.gauge("data.service.epoch").set(self._epoch)
        telemetry.gauge("data.service.leased_ranges").set(
            len(self._outstanding))

    def _requeue_worker_locked(self, worker: int, reason: str) -> int:
        poss = sorted(self._worker_pos.pop(worker, ()))
        for pos in poss:
            if not self._retired[pos]:
                self._outstanding.pop(pos, None)
                self._pending.append(pos)
        self._pending.sort()
        n = len(poss)
        if n:
            self._releases += n
            telemetry.counter("data.service.releases",
                              reason=reason).inc(n)
            telemetry.record_event("data_service", transition="release",
                                   worker=worker, reason=reason, ranges=n)
        return n

    def _sweep_locked(self) -> None:
        for worker in self.membership.sweep():
            self._requeue_worker_locked(worker, reason="lease")

    def _advance_epoch_locked(self) -> None:
        if self._epoch + 1 >= self.num_epochs:
            self._epoch = self.num_epochs
            self._exhausted = True
            telemetry.record_event("data_service", transition="exhausted")
        else:
            self._epoch += 1
            self._perm = self._epoch_perm(self._epoch)
            self._next_pos = 0
            self._pending = []
            self._outstanding = {}
            self._worker_pos = {}
            self._retired = np.zeros(self.num_ranges, bool)
            self._watermark = 0
            telemetry.record_event("data_service", transition="epoch",
                                   epoch=self._epoch)

    def _lease_locked(self, worker: int, max_ranges: int) -> dict:
        if self._exhausted:
            return {"ranges": [], "epoch": int(self._epoch),
                    "exhausted": True}
        granted: List[list] = []
        while len(granted) < max_ranges:
            if self._pending:
                pos = self._pending.pop(0)
            elif self._next_pos < self.num_ranges:
                pos, self._next_pos = self._next_pos, self._next_pos + 1
            else:
                break
            self._outstanding[pos] = worker
            self._worker_pos.setdefault(worker, set()).add(pos)
            start, stop = self._range_bounds(int(self._perm[pos]))
            granted.append([int(pos), start, stop])
        if granted:
            telemetry.counter("data.service.leases").inc(len(granted))
        self._publish_gauges_locked()
        reply = {"ranges": granted, "epoch": int(self._epoch),
                 "exhausted": False}
        if not granted:
            # nothing grantable but the epoch is not done: ranges are
            # outstanding on other workers — poll again (or inherit them
            # when their lease lapses)
            reply["wait"] = True
        return reply

    def _ack_locked(self, worker: int, epoch: int,
                    positions: Sequence[int]) -> dict:
        if epoch != self._epoch or self._exhausted:
            # an epoch the coordinator has moved past: every position in
            # it is already retired — idempotent no-op
            telemetry.counter("data.service.stale_acks").inc(len(positions))
            return {"retired": 0, "stale": len(positions),
                    "epoch": int(self._epoch)}
        retired = stale = 0
        for pos in positions:
            pos = int(pos)
            if not 0 <= pos < self.num_ranges:
                raise ValueError(f"ack position {pos} outside "
                                 f"[0, {self.num_ranges})")
            if self._retired[pos]:
                stale += 1  # double-ack (or a zombie after re-retire)
                continue
            owner = self._outstanding.pop(pos, None)
            if owner != worker:
                # re-leased away (the acker's lease lapsed) or never
                # dispatched: retire anyway — the bytes landed — but
                # account the anomaly
                stale += 1
                if owner is not None:
                    self._worker_pos.get(owner, set()).discard(pos)
                if pos in self._pending:
                    self._pending.remove(pos)
            else:
                self._worker_pos.get(worker, set()).discard(pos)
            self._retired[pos] = True
            retired += 1
        while (self._watermark < self.num_ranges
               and self._retired[self._watermark]):
            self._watermark += 1
        if retired:
            telemetry.counter("data.service.acks").inc(retired)
        if stale:
            telemetry.counter("data.service.stale_acks").inc(stale)
        epoch_done = bool(self._retired.all())
        if epoch_done:
            self._advance_epoch_locked()
        self._publish_gauges_locked()
        return {"retired": retired, "stale": stale,
                "epoch_done": epoch_done, "epoch": int(self._epoch)}

    # -- (cid, seq) replay cache (the PS commit-dedup shape) ---------------
    def _dedup_get(self, cid, seq) -> Optional[dict]:
        with self._dedup_lock:
            return self._dedup.get(cid, {}).get(seq)

    def _dedup_put(self, cid, seq, reply: dict) -> None:
        with self._dedup_lock:
            replies = self._dedup.setdefault(cid, OrderedDict())
            replies[seq] = reply
            while len(replies) > self.DEDUP_CACHE:
                replies.popitem(last=False)

    # -- introspection -----------------------------------------------------
    def status_digest(self) -> dict:
        """The compact DATA digest: merged into the health ``status`` op
        and the source of ``health.cli watch --table``'s DATA line."""
        with self._lock:
            return {
                "data": {
                    "epoch": int(self._epoch),
                    "cursor": int(self._watermark),
                    "ranges": int(self.num_ranges),
                    "leased": len(self._outstanding),
                    "pending": len(self._pending),
                    "releases": int(self._releases),
                    "exhausted": bool(self._exhausted),
                },
                "membership": self.membership.status(),
            }

    # -- per-connection handler -------------------------------------------
    def _serve(self, conn: socket.socket):
        with self._conn_lock:
            self._conns.add(conn)
        try:
            with conn:
                while True:
                    try:
                        header, blobs = _recv(conn)
                    except ConnectionError:
                        return
                    if not check_token(self.token, header):
                        telemetry.counter(
                            "data.service.server.auth_failures").inc()
                        _sendall(conn, {"error": "authentication failed"})
                        return
                    try:
                        self._dispatch(conn, header)
                    except ConnectionError:
                        return  # chaos reset / peer vanished; service lives
        except Exception:
            if self._running:
                raise
        finally:
            with self._conn_lock:
                self._conns.discard(conn)

    def _dispatch(self, conn, header: dict):
        op = header["op"]
        act = fault.chaos("data.lease")
        if act is not None:
            if act.action == "delay":
                time.sleep(act.delay_s)
            elif act.action == "kill":
                self.kill(reason="chaos")
                raise ConnectionError("chaos: data coordinator killed")
            else:  # either reset flavor: drop the connection, no reply
                conn.close()
                raise ConnectionError("chaos: server reset the connection")
        telemetry.counter("data.service.server.dispatch", op=op).inc()
        if op in HEALTH_OPS:
            _sendall(conn, handle_health_op(
                op, header, extra_status=self.status_digest()))
            return
        if op == "data_register":
            worker = int(header["worker"])
            lease = self.membership.register(worker)
            _sendall(conn, {"lease_s": lease,
                            "serves_data": self.dataset is not None,
                            "total_rows": self.total_rows,
                            "range_size": self.range_size,
                            "num_ranges": self.num_ranges,
                            "num_epochs": self.num_epochs})
        elif op == "data_lease":
            worker = int(header["worker"])
            cid, seq = header.get("cid"), header.get("seq")
            cached = None if cid is None else self._dedup_get(cid, seq)
            if cached is not None:
                telemetry.counter("data.service.dedup_hits").inc()
                _sendall(conn, cached)
                return
            # a lease request is proof of life: register renews (and
            # re-admits a lapsed worker — its old ranges were re-queued
            # by the sweep; it simply leases fresh ones)
            self.membership.register(worker)
            with self._lock:
                self._sweep_locked()
                reply = self._lease_locked(
                    worker, max(1, int(header.get("max_ranges", 1))))
            if cid is not None:
                self._dedup_put(cid, seq, reply)
            _sendall(conn, reply)
        elif op == "data_ack":
            worker = int(header["worker"])
            cid, seq = header.get("cid"), header.get("seq")
            cached = None if cid is None else self._dedup_get(cid, seq)
            if cached is not None:
                telemetry.counter("data.service.dedup_hits").inc()
                _sendall(conn, cached)
                return
            self.membership.register(worker)
            with self._lock:
                self._sweep_locked()
                reply = self._ack_locked(worker, int(header["epoch"]),
                                         header.get("positions", ()))
            if cid is not None:
                self._dedup_put(cid, seq, reply)
            _sendall(conn, reply)
        elif op == "data_fetch":
            if self.dataset is None:
                _sendall(conn, {
                    "error": "this coordinator was constructed without a "
                             "dataset; it leases order only — slice rows "
                             "locally",
                    "error_kind": "no_data"})
                return
            start, stop = int(header["start"]), int(header["stop"])
            if not 0 <= start <= stop <= self.total_rows:
                _sendall(conn, {
                    "error": f"range [{start}, {stop}) outside "
                             f"[0, {self.total_rows})",
                    "error_kind": "bad_range"})
                return
            cols = header.get("cols") or self.dataset.columns
            names, blobs = _encode_columns(
                {c: self.dataset[c][start:stop] for c in cols})
            telemetry.counter("data.service.fetch_rows").inc(stop - start)
            _sendall(conn, {"cols": names}, blobs)
        elif op == "data_cursor":
            carry = self.cursor_carry()
            with self._lock:
                digest = {
                    "cursor": [int(carry[0]), int(carry[1])],
                    "epoch": int(self._epoch),
                    "watermark": int(self._watermark),
                    "releases": int(self._releases),
                    "exhausted": bool(self._exhausted),
                }
            _sendall(conn, digest)
        elif op == "data_restore":
            try:
                self.restore_cursor(header["cursor"])
            except ValueError as e:
                _sendall(conn, {"error": str(e), "error_kind": "bad_cursor"})
                return
            _sendall(conn, {"ok": True})
        elif op == "data_deregister":
            worker = int(header["worker"])
            with self._lock:
                self._requeue_worker_locked(worker, reason="deregister")
                self._publish_gauges_locked()
            self.membership.deregister(worker)
            _sendall(conn, {"ok": True})
        else:
            _sendall(conn, {"error": f"unknown op {op!r}",
                            "error_kind": "unknown_op"})


class DataServiceClient:
    """One worker's connection to a :class:`DataCoordinator`.

    NOT thread-safe — the streaming contract is one client per worker
    thread (unlike the pipelined PS client, data ops are coarse enough
    that sharing a socket buys nothing). Reconnect + bounded exponential
    backoff ride every op; exhaustion raises the typed
    :class:`DataServiceUnavailable`. Mutating ops (lease/ack) carry
    ``(cid, seq)`` so a retried request that DID apply server-side replays
    the cached reply instead of re-executing.
    """

    def __init__(self, address: str, worker: int,
                 token: Optional[str] = None,
                 timeout: float = 30.0,
                 op_timeout: Optional[float] = 30.0,
                 retry: Optional[comms.RetryPolicy] = None):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self.worker = int(worker)
        self.token = token
        self._timeout = timeout
        self._op_timeout = op_timeout
        self.retry = retry if retry is not None else comms.RetryPolicy()
        self._cid = os.urandom(8).hex()
        self._seq = 0
        self._sock: Optional[socket.socket] = None
        self._closed = False
        self.meta: dict = {}

    # -- transport ---------------------------------------------------------
    def _ensure_connected(self) -> socket.socket:
        if self._sock is None:
            if self._closed:
                raise DataServiceUnavailable(
                    f"client for {self._addr[0]}:{self._addr[1]} is closed")
            sock = socket.create_connection(self._addr,
                                            timeout=self._timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
            telemetry.counter("data.service.client.reconnects").inc()
        return self._sock

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _send_once(self, header: dict) -> Tuple[dict, list]:
        sock = self._ensure_connected()
        act = fault.chaos("data.fetch")
        if act is not None:
            if act.action == "delay":
                time.sleep(act.delay_s)
            elif act.action == "reset":
                self._teardown()
                raise ConnectionError("chaos: connection reset before send")
        dropped = act is not None and act.action == "drop"
        if not dropped:
            _sendall(sock, header)
            if act is not None and act.action == "reset_after_send":
                # the request reached the wire: the server applies it and
                # replies into a closed socket — the (cid, seq) scenario
                self._teardown()
                raise ConnectionError("chaos: connection reset after send")
        else:
            # a swallowed request has no reply coming: ride out a bounded
            # wait, then declare the connection dead (what a real lost
            # frame amounts to on a serial request/reply socket)
            time.sleep(min(self._op_timeout or 1.0, 1.0))
            self._teardown()
            raise socket.timeout("chaos: request dropped")
        try:
            sock.settimeout(self._op_timeout)
            resp, blobs = _recv(sock)
        except (ConnectionError, socket.timeout, OSError):
            self._teardown()
            raise
        if "error" in resp:
            raise RuntimeError(
                f"data op {header.get('op')!r} against "
                f"{self._addr[0]}:{self._addr[1]}: {resp['error']}")
        return resp, blobs

    def _request(self, header: dict) -> Tuple[dict, list]:
        op = header.get("op", "?")
        if self.token is not None:
            header = {**header, "token": self.token}
        attempt = 0
        t0 = time.perf_counter()
        while True:
            try:
                resp, blobs = self._send_once(header)
                break
            except (ConnectionError, socket.timeout, OSError) as e:
                attempt += 1
                if self._closed or attempt > self.retry.max_retries:
                    telemetry.counter("data.service.client.unavailable",
                                      op=op).inc()
                    raise DataServiceUnavailable(
                        f"data coordinator {self._addr[0]}:{self._addr[1]} "
                        f"unavailable: {op} failed after "
                        f"{attempt - 1} retries ({e})") from e
                telemetry.counter("data.service.client.retries",
                                  op=op).inc()
                time.sleep(self.retry.delay(attempt))
        telemetry.histogram("data.service.client.rtt_s", op=op).record(
            time.perf_counter() - t0)
        return resp, blobs

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    # -- protocol verbs ----------------------------------------------------
    def register(self) -> dict:
        resp, _ = self._request({"op": "data_register",
                                 "worker": self.worker})
        self.meta = resp
        return resp

    def lease(self, max_ranges: int = 1) -> dict:
        """One lease round-trip: ``{"ranges": [[pos, start, stop], ...],
        "epoch": e, "exhausted": bool, "wait": bool?}``."""
        resp, _ = self._request({"op": "data_lease", "worker": self.worker,
                                 "max_ranges": int(max_ranges),
                                 "cid": self._cid,
                                 "seq": self._next_seq()})
        return resp

    def ack(self, epoch: int, positions: Sequence[int]) -> dict:
        resp, _ = self._request({"op": "data_ack", "worker": self.worker,
                                 "epoch": int(epoch),
                                 "positions": [int(p) for p in positions],
                                 "cid": self._cid,
                                 "seq": self._next_seq()})
        return resp

    def fetch(self, start: int, stop: int,
              cols: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        header = {"op": "data_fetch", "start": int(start), "stop": int(stop)}
        if cols is not None:
            header["cols"] = list(cols)
        resp, blobs = self._request(header)
        return _decode_columns(resp["cols"], blobs)

    def cursor(self) -> dict:
        resp, _ = self._request({"op": "data_cursor"})
        return resp

    def restore(self, carry) -> None:
        self._request({"op": "data_restore",
                       "cursor": [int(v) for v in
                                  np.asarray(carry).reshape(-1)]})

    def deregister(self) -> None:
        self._request({"op": "data_deregister", "worker": self.worker})

    def close(self) -> None:
        self._closed = True
        self._teardown()

    def __enter__(self) -> "DataServiceClient":
        self.register()
        return self

    def __exit__(self, *exc) -> None:
        try:
            if self._sock is not None and not self._closed:
                self.deregister()
        except (RuntimeError, OSError):
            pass
        self.close()


def stream_ranges(client: DataServiceClient,
                  dataset=None,
                  cols: Optional[Sequence[str]] = None,
                  max_ranges: int = 1,
                  poll_s: float = 0.02,
                  sleep_fn: Callable[[float], None] = time.sleep):
    """Generator driving one worker's lease → materialize → ack loop.

    Yields ``(epoch, stream_pos, row_start, row_stop, columns_dict)`` per
    leased range, in this worker's lease order; the GLOBAL stream order is
    recovered by sorting on ``(epoch, stream_pos)`` — that key is assigned
    by the coordinator's seeded permutation, so it is identical whatever
    the worker count or churn. Rows come from ``dataset`` (local-slice
    mode) when given, else over the wire via ``data_fetch`` (streaming
    admission; requires a coordinator constructed with ``dataset=``).

    The ack for a range is sent AFTER its item is yielded and the consumer
    asks for the next one — i.e. after the consumer has landed the
    batches. A worker killed mid-range therefore loses nothing: its
    unacked ranges re-lease to survivors (DESIGN.md §20's loss-window
    statement covers the consumed-but-unacked corner).
    """
    if dataset is None and not client.meta.get("serves_data"):
        raise ValueError(
            "no local dataset and the coordinator does not serve bytes "
            "(constructed without dataset=); one side must hold the rows")
    while True:
        resp = client.lease(max_ranges=max_ranges)
        if resp.get("exhausted"):
            return
        ranges = resp.get("ranges", ())
        if not ranges:
            sleep_fn(poll_s)  # tail of an epoch: ranges outstanding
            continue          # elsewhere — poll (or inherit on lapse)
        epoch = int(resp["epoch"])
        done: List[int] = []
        try:
            for pos, start, stop in ranges:
                if dataset is not None:
                    want = list(cols) if cols is not None else None
                    rows = {c: np.asarray(dataset[c][start:stop])
                            for c in (want or dataset.columns)}
                else:
                    rows = client.fetch(start, stop, cols=cols)
                yield int(epoch), int(pos), int(start), int(stop), rows
                done.append(int(pos))
        finally:
            # landed ranges are acked even when the consumer abandons the
            # generator mid-lease; unyielded ones re-lease via lapse. An
            # unreachable (or closed) coordinator here is not an error:
            # failing to ack only widens the replay window — the safe
            # direction — and raising out of a GeneratorExit would turn
            # every abandon-during-outage into a crash.
            if done:
                try:
                    client.ack(epoch, done)
                except DataServiceUnavailable:
                    pass
