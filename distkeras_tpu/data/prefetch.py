"""Background prefetch: a reader thread ahead of the device.

The staging generators (`substrate.stage_epoch_chunks`,
`tensor.stage_step_chunks`) do host-side work per chunk — disk reads for
file-backed datasets, the O(chunk) stack/copy, and the (async) device_put
dispatch. Running the generator on a daemon thread with a small bounded
queue overlaps ALL of that with device compute on the previous chunk; the
consumer just drains the queue. This is the TPU-native stand-in for the
reference's Spark executors prefetching partition iterators.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Iterable, Iterator, TypeVar

from distkeras_tpu import telemetry

T = TypeVar("T")

_DONE = object()


def prefetch(it: Iterable[T], depth: int = 1) -> Iterator[T]:
    """Iterate ``it`` on a background thread, keeping up to ``depth`` items
    queued. Exceptions raised by the producer re-raise at the consumer's
    ``next()`` with the producer-side frames preserved as text on
    ``exc.producer_traceback`` (and a ``data.prefetch.producer_errors``
    count); ordering is preserved.

    Memory bound: at most ``depth + 1`` items exist beyond the one the
    consumer holds (``depth`` queued plus one the blocked producer has
    already built) — with the default ``depth=1`` that is classic double
    buffering. If the consumer abandons the generator (break / exception),
    its ``finally`` signals the producer, which drops its pending item and
    exits instead of blocking forever holding device buffers.
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    abandoned = threading.Event()
    # queue occupancy seen by the consumer: persistently 0 = producer-bound
    # (disk/staging is the bottleneck), persistently `depth` = device-bound
    depth_gauge = telemetry.gauge("data.prefetch.queue_depth")
    depth_hist = telemetry.histogram("data.prefetch.queue_depth_samples")
    wait_hist = telemetry.histogram("data.prefetch.producer_wait_s")
    puts = telemetry.counter("data.prefetch.puts")
    # one poll interval of the give-up loop below: an uncontended put
    # completes well inside this, so only waits beyond it are real
    # backpressure (recording every put drowned the histogram in ~0 s
    # fast-path samples and dragged the reported mean toward zero)
    _POLL_S = 0.1

    def _put(item) -> bool:
        """put that gives up when the consumer is gone."""
        t0 = time.perf_counter()
        while not abandoned.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                puts.inc()
                # time the producer sat blocked on a full queue — the
                # backpressure the bounded buffer applies. Uncontended
                # fast-path puts (shorter than one poll interval) are
                # counted by `puts` but kept out of the histogram.
                waited = time.perf_counter() - t0
                if waited > _POLL_S:
                    wait_hist.record(waited)
                return True
            except queue.Full:
                continue
        return False

    def run():
        try:
            for item in it:
                if not _put((False, item)):
                    return
        except BaseException as e:  # propagate, don't swallow
            # the exception re-raises on the CONSUMER thread, where its
            # __traceback__ stops at this thread's boundary — carry the
            # producer-side frames (the disk read / staging code that
            # actually blew up) along as text
            telemetry.counter("data.prefetch.producer_errors").inc()
            _put((True, (e, traceback.format_exc())))
            return
        _put((False, _DONE))

    thread = threading.Thread(target=run, daemon=True,
                              name="distkeras-prefetch")
    thread.start()
    try:
        while True:
            size = q.qsize()
            depth_gauge.set(size)
            depth_hist.record(size)
            is_err, item = q.get()
            if is_err:
                exc, tb_text = item
                # attach the producer-side frames for handlers/logs; the
                # chained note keeps `raise` semantics (type and args)
                # identical to re-raising the original
                exc.producer_traceback = tb_text
                raise exc
            if item is _DONE:
                return
            yield item
    finally:
        abandoned.set()
