from distkeras_tpu.data.dataset import Dataset, synthetic_mnist

__all__ = ["Dataset", "synthetic_mnist"]
