from distkeras_tpu.data.dataset import Dataset, ShardedColumn, synthetic_mnist
from distkeras_tpu.data.prefetch import prefetch

__all__ = ["Dataset", "ShardedColumn", "prefetch", "synthetic_mnist"]
