from distkeras_tpu.data.dataset import (
    Dataset,
    PermutedColumn,
    ShardedColumn,
    synthetic_mnist,
)
from distkeras_tpu.data.global_shards import GlobalShards, ShardingError
from distkeras_tpu.data.prefetch import prefetch
from distkeras_tpu.data.service import (
    DataCoordinator,
    DataServiceClient,
    DataServiceUnavailable,
    stream_ranges,
)

__all__ = ["DataCoordinator", "DataServiceClient", "DataServiceUnavailable",
           "Dataset", "GlobalShards", "PermutedColumn", "ShardedColumn",
           "ShardingError", "prefetch", "stream_ranges", "synthetic_mnist"]
