from distkeras_tpu.data.dataset import (
    Dataset,
    PermutedColumn,
    ShardedColumn,
    synthetic_mnist,
)
from distkeras_tpu.data.prefetch import prefetch

__all__ = ["Dataset", "PermutedColumn", "ShardedColumn", "prefetch",
           "synthetic_mnist"]
