from distkeras_tpu.data.dataset import (
    Dataset,
    PermutedColumn,
    ShardedColumn,
    synthetic_mnist,
)
from distkeras_tpu.data.global_shards import GlobalShards
from distkeras_tpu.data.prefetch import prefetch

__all__ = ["Dataset", "GlobalShards", "PermutedColumn", "ShardedColumn",
           "prefetch", "synthetic_mnist"]
