"""Columnar in-memory dataset — the Spark-DataFrame stand-in.

Reference parity: dist-keras consumes Spark DataFrames with named feature /
label columns, repartitions them per worker, and iterates rows per partition
(``distkeras/trainers.py``/``workers.py`` — unverified, mount empty). The
TPU-native equivalent is a host-resident columnar store (dict of NumPy
arrays) with the same vocabulary: named columns, ``shuffle``, ``repartition``
into per-worker shards, and *batched* iteration with static shapes (pad or
drop ragged tails — XLA requires fixed shapes).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from distkeras_tpu.utils import rng


class ShardedColumn:
    """Lazy concatenation of per-file array shards (memmaps stay on disk).

    Presents just enough of the ndarray protocol for the data path: length,
    shape/dtype, contiguous slicing (returns a trimmed *view* — no bytes
    read), integer row access, and materialization via ``np.asarray``. The
    staging layer slices chunks out of worker shards and materializes only
    those, so an epoch never has to exist in host RAM at once.
    """

    def __init__(self, parts: Sequence[np.ndarray]):
        if not parts:
            raise ValueError("ShardedColumn needs at least one part")
        tails = {p.shape[1:] for p in parts}
        dtypes = {p.dtype for p in parts}
        if len(tails) != 1 or len(dtypes) != 1:
            raise ValueError(
                f"Shard shape/dtype mismatch: shapes {sorted(tails)}, "
                f"dtypes {sorted(map(str, dtypes))}")
        self.parts = list(parts)
        self._offsets = np.cumsum([0] + [len(p) for p in parts])

    def __len__(self) -> int:
        return int(self._offsets[-1])

    @property
    def shape(self):
        return (len(self),) + self.parts[0].shape[1:]

    @property
    def dtype(self):
        return self.parts[0].dtype

    def __array__(self, dtype=None, copy=None):
        out = np.concatenate([np.asarray(p) for p in self.parts])
        return out.astype(dtype) if dtype is not None else out

    def __getitem__(self, key):
        if isinstance(key, slice):
            lo, hi, step = key.indices(len(self))
            if step != 1:
                return np.asarray(self)[key]
            views = []
            for p, off in zip(self.parts, self._offsets[:-1]):
                a, b = max(lo - off, 0), min(hi - off, len(p))
                if a < b:
                    views.append(p[a:b])
            if not views:
                views = [self.parts[0][:0]]
            return views[0] if len(views) == 1 else ShardedColumn(views)
        if np.isscalar(key) or isinstance(key, (int, np.integer)):
            i = int(key) + (len(self) if key < 0 else 0)
            part = int(np.searchsorted(self._offsets, i, side="right")) - 1
            return self.parts[part][i - self._offsets[part]]
        idx = np.asarray(key)
        if idx.ndim == 1 and \
                (np.issubdtype(idx.dtype, np.integer) or idx.size == 0):
            # per-part gather: reads O(len(idx)) rows from disk, never the
            # whole column (memmap fancy indexing touches only those pages)
            idx = idx.astype(np.int64, copy=False)
            if idx.size and (idx.min() < -len(self) or
                             idx.max() >= len(self)):
                raise IndexError(
                    f"index out of bounds for ShardedColumn of "
                    f"length {len(self)}: {key!r}")
            idx = np.where(idx < 0, idx + len(self), idx)
            out = np.empty((len(idx),) + self.parts[0].shape[1:], self.dtype)
            part_of = np.searchsorted(self._offsets, idx, side="right") - 1
            for p in np.unique(part_of):
                m = part_of == p
                out[m] = self.parts[p][idx[m] - self._offsets[p]]
            return out
        return np.asarray(self)[key]  # boolean/N-d keys materialize


class PermutedColumn:
    """Lazy row-permuted view of a (possibly file-backed) column.

    ``shuffle()`` on a lazy column keeps the O(n) permutation INDEX
    (8 bytes/row — trivial even at ImageNet scale) but defers the row
    gather: slicing returns another lazy view, and only materialization
    (``np.asarray`` of a chunk/batch slice) reads the underlying rows —
    O(slice) disk reads, never the whole column. Sample order is
    bit-identical to the materializing shuffle: the same
    ``rng.permutation`` indices, applied late instead of eagerly.
    """

    def __init__(self, base, perm: np.ndarray):
        self.base = base
        self.perm = np.asarray(perm)

    def __len__(self) -> int:
        return len(self.perm)

    @property
    def shape(self):
        return (len(self.perm),) + tuple(self.base.shape[1:])

    @property
    def dtype(self):
        return self.base.dtype

    def _gather(self, idx: np.ndarray) -> np.ndarray:
        # memmap / ShardedColumn fancy indexing reads O(len(idx)) rows
        return np.asarray(self.base[idx])

    def __array__(self, dtype=None, copy=None):
        out = self._gather(self.perm)
        return out.astype(dtype) if dtype is not None else out

    def __getitem__(self, key):
        if isinstance(key, slice):
            return PermutedColumn(self.base, self.perm[key])  # stays lazy
        if np.isscalar(key) or isinstance(key, (int, np.integer)):
            return self.base[int(self.perm[key])]
        return self._gather(self.perm[np.asarray(key)])


ColumnLike = Union[np.ndarray, ShardedColumn, PermutedColumn]


class Dataset:
    """An immutable set of equal-length named columns."""

    def __init__(self, columns: Dict[str, ColumnLike]):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        n = {len(v) for v in columns.values()}
        if len(n) != 1:
            raise ValueError(f"Column length mismatch: "
                             f"{ {k: len(v) for k, v in columns.items()} }")
        # ShardedColumns, memmaps and PermutedColumns pass through
        # un-materialized (memmap is kept as its own type so laziness stays
        # visible downstream)
        self._columns = {
            k: v if isinstance(v, (ShardedColumn, np.memmap, PermutedColumn))
            else np.asarray(v)
            for k, v in columns.items()}

    # -- basic accessors ----------------------------------------------------
    def __len__(self) -> int:
        return len(next(iter(self._columns.values())))

    def __contains__(self, col: str) -> bool:
        return col in self._columns

    def __getitem__(self, col: str) -> np.ndarray:
        return self._columns[col]

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        """Functional 'withColumn' — the transformer output path."""
        new = dict(self._columns)
        new[name] = np.asarray(values)
        return Dataset(new)

    def select(self, cols: Sequence[str]) -> "Dataset":
        return Dataset({c: self._columns[c] for c in cols})

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._columns.items()})

    # -- distribution-shaped ops -------------------------------------------
    def shuffle(self, seed: int = 0) -> "Dataset":
        """utils.shuffle(df) parity, but deterministic by seed.

        In-memory columns are gathered eagerly (through the native threaded
        assembler when available, data/native.py). File-backed columns
        (memmap / ShardedColumn) become lazy :class:`PermutedColumn` views —
        the streaming shuffle: only the permutation index (8 bytes/row) is
        materialized now; rows are read from disk O(chunk) at a time as the
        staging layer slices them. Indices are identical on every path, so
        numerics do not depend on which one executed."""
        from distkeras_tpu.data import native

        perm = rng.permutation(seed, len(self))
        out: Dict[str, ColumnLike] = {}
        for k, v in self._columns.items():
            if isinstance(v, PermutedColumn):
                out[k] = PermutedColumn(v.base, v.perm[perm])  # compose lazily
            elif isinstance(v, (ShardedColumn, np.memmap)):
                out[k] = PermutedColumn(v, perm)
            else:
                out[k] = native.gather_rows(np.asarray(v), perm)
        return Dataset(out)

    def repartition(self, num_partitions: int) -> List["Dataset"]:
        """Split into contiguous near-equal shards (Spark repartition parity;
        call shuffle() first for the randomized behavior). Slice-based, so
        shards of memmap/file-backed columns stay views — no bytes read."""
        sizes = np.full(num_partitions, len(self) // num_partitions)
        sizes[:len(self) % num_partitions] += 1  # np.array_split's split
        bounds = np.cumsum(np.concatenate([[0], sizes]))
        return [Dataset({k: v[lo:hi] for k, v in self._columns.items()})
                for lo, hi in zip(bounds[:-1], bounds[1:])]

    def batches(self, batch_size: int, cols: Optional[Sequence[str]] = None,
                drop_remainder: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """Static-shape minibatches. The ragged tail is dropped by default
        (XLA recompiles per shape; the reference's row-iterator had no such
        constraint but also no compiled step)."""
        cols = list(cols) if cols is not None else self.columns
        n = len(self)
        limit = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, limit, batch_size):
            # np.asarray materializes lazy columns (ShardedColumn/memmap)
            # batch by batch — consumers hand these straight to jit
            yield {c: np.asarray(self._columns[c][start:start + batch_size])
                   for c in cols}

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        n = len(self)
        return n // batch_size if drop_remainder else -(-n // batch_size)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_arrays(**columns) -> "Dataset":
        return Dataset(columns)

    @staticmethod
    def from_files(columns: Dict[str, Union[str, Sequence[str]]],
                   mmap: bool = True) -> "Dataset":
        """File-backed dataset from ``.npy`` files: one path or a list of
        shard paths per column (SURVEY §7's "input pipeline" hard part —
        ImageNet-scale epochs must be feedable without host-RAM residency).

        With ``mmap=True`` (default) every file is ``np.load``-ed with
        ``mmap_mode="r"``: rows are read from disk only when a staging
        chunk materializes them, so training streams the epoch in O(chunk)
        host memory (`substrate.stage_epoch_chunks` + `staging_rounds=`).
        Multi-file columns are presented as one logical column via
        :class:`ShardedColumn` — shard boundaries need not align with
        worker or chunk boundaries.

        ``shuffle()`` on a file-backed dataset is a streaming shuffle: it
        returns lazy :class:`PermutedColumn` views and rows are read from
        disk O(chunk) at a time during staging (random-access reads; for
        spinning disks, pre-shuffled shard files are still friendlier).
        """
        cols: Dict[str, ColumnLike] = {}
        mode = "r" if mmap else None
        for name, paths in columns.items():
            if isinstance(paths, (str, bytes)) or hasattr(paths, "__fspath__"):
                paths = [paths]
            parts = [np.load(p, mmap_mode=mode) for p in paths]
            cols[name] = parts[0] if len(parts) == 1 else ShardedColumn(parts)
        return Dataset(cols)

    @staticmethod
    def concat(parts: Sequence["Dataset"]) -> "Dataset":
        """Concatenate datasets row-wise. When any input column is lazy
        (memmap / ShardedColumn / PermutedColumn) the result column is a
        ShardedColumn over the parts — no bytes are read; in-memory inputs
        concatenate eagerly as before."""
        cols = parts[0].columns
        out: Dict[str, ColumnLike] = {}
        for c in cols:
            vs = [p[c] for p in parts]
            lazy = any(isinstance(
                v, (ShardedColumn, np.memmap, PermutedColumn)) for v in vs)
            # mixed dtypes fall back to eager concatenation, which PROMOTES
            # (f32 + f64 -> f64) the way plain np.concatenate always did;
            # the lazy view requires one common dtype
            if lazy and len({np.dtype(v.dtype) for v in vs}) == 1:
                out[c] = vs[0] if len(vs) == 1 else ShardedColumn(vs)
            else:
                out[c] = np.concatenate([np.asarray(v) for v in vs])
        return Dataset(out)


def synthetic_mnist(n: int = 4096, seed: int = 0,
                    features_col: str = "features",
                    label_col: str = "label") -> Dataset:
    """Deterministic MNIST-shaped synthetic data (for tests and smoke benches).

    Labels are a (noisy) linear function of the features so that learning is
    actually possible and convergence tests mean something.
    """
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 784)).astype(np.float32)
    w = rng.standard_normal((784, 10)).astype(np.float32) * 0.3
    logits = x @ w + 0.05 * rng.standard_normal((n, 10)).astype(np.float32)
    y = logits.argmax(-1).astype(np.int32)
    onehot = np.eye(10, dtype=np.float32)[y]
    return Dataset({features_col: x, label_col: onehot, "label_index": y})
