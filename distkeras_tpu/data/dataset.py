"""Columnar in-memory dataset — the Spark-DataFrame stand-in.

Reference parity: dist-keras consumes Spark DataFrames with named feature /
label columns, repartitions them per worker, and iterates rows per partition
(``distkeras/trainers.py``/``workers.py`` — unverified, mount empty). The
TPU-native equivalent is a host-resident columnar store (dict of NumPy
arrays) with the same vocabulary: named columns, ``shuffle``, ``repartition``
into per-worker shards, and *batched* iteration with static shapes (pad or
drop ragged tails — XLA requires fixed shapes).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from distkeras_tpu.utils import rng


class Dataset:
    """An immutable set of equal-length named columns."""

    def __init__(self, columns: Dict[str, np.ndarray]):
        if not columns:
            raise ValueError("Dataset needs at least one column")
        n = {len(v) for v in columns.values()}
        if len(n) != 1:
            raise ValueError(f"Column length mismatch: "
                             f"{ {k: len(v) for k, v in columns.items()} }")
        self._columns = {k: np.asarray(v) for k, v in columns.items()}

    # -- basic accessors ----------------------------------------------------
    def __len__(self) -> int:
        return len(next(iter(self._columns.values())))

    def __contains__(self, col: str) -> bool:
        return col in self._columns

    def __getitem__(self, col: str) -> np.ndarray:
        return self._columns[col]

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def with_column(self, name: str, values: np.ndarray) -> "Dataset":
        """Functional 'withColumn' — the transformer output path."""
        new = dict(self._columns)
        new[name] = np.asarray(values)
        return Dataset(new)

    def select(self, cols: Sequence[str]) -> "Dataset":
        return Dataset({c: self._columns[c] for c in cols})

    def take(self, n: int) -> "Dataset":
        return Dataset({k: v[:n] for k, v in self._columns.items()})

    # -- distribution-shaped ops -------------------------------------------
    def shuffle(self, seed: int = 0) -> "Dataset":
        """utils.shuffle(df) parity, but deterministic by seed. The row
        gather runs through the native threaded assembler when available
        (data/native.py); indices are identical either way, so numerics
        do not depend on which path executed."""
        from distkeras_tpu.data import native

        perm = rng.permutation(seed, len(self))
        return Dataset({k: native.gather_rows(v, perm)
                        for k, v in self._columns.items()})

    def repartition(self, num_partitions: int) -> List["Dataset"]:
        """Split into contiguous near-equal shards (Spark repartition parity;
        call shuffle() first for the randomized behavior)."""
        idx = np.array_split(np.arange(len(self)), num_partitions)
        return [Dataset({k: v[i] for k, v in self._columns.items()})
                for i in idx]

    def batches(self, batch_size: int, cols: Optional[Sequence[str]] = None,
                drop_remainder: bool = True) -> Iterator[Dict[str, np.ndarray]]:
        """Static-shape minibatches. The ragged tail is dropped by default
        (XLA recompiles per shape; the reference's row-iterator had no such
        constraint but also no compiled step)."""
        cols = list(cols) if cols is not None else self.columns
        n = len(self)
        limit = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, limit, batch_size):
            yield {c: self._columns[c][start:start + batch_size] for c in cols}

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        n = len(self)
        return n // batch_size if drop_remainder else -(-n // batch_size)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_arrays(**columns) -> "Dataset":
        return Dataset(columns)

    @staticmethod
    def concat(parts: Sequence["Dataset"]) -> "Dataset":
        cols = parts[0].columns
        return Dataset({c: np.concatenate([p[c] for p in parts]) for c in cols})


def synthetic_mnist(n: int = 4096, seed: int = 0,
                    features_col: str = "features",
                    label_col: str = "label") -> Dataset:
    """Deterministic MNIST-shaped synthetic data (for tests and smoke benches).

    Labels are a (noisy) linear function of the features so that learning is
    actually possible and convergence tests mean something.
    """
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 784)).astype(np.float32)
    w = rng.standard_normal((784, 10)).astype(np.float32) * 0.3
    logits = x @ w + 0.05 * rng.standard_normal((n, 10)).astype(np.float32)
    y = logits.argmax(-1).astype(np.int32)
    onehot = np.eye(10, dtype=np.float32)[y]
    return Dataset({features_col: x, label_col: onehot, "label_index": y})
