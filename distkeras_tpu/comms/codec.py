"""Wire codecs for the parameter-server path: pluggable leaf encodings.

A :class:`Codec` turns one pytree leaf (a numpy array whose shape/dtype
both ends agreed on out of band) into a self-contained wire blob and back.
Blobs carry their own per-leaf metadata inline (quantization scale/offset
as a fixed-size prefix), so the message framing stays "a list of blobs" —
no header schema changes per codec.

Implementations:

- :class:`RawCodec` — native bytes, exact (today's behavior).
- :class:`Fp16Codec` / :class:`Bf16Codec` — cast-on-wire for float leaves,
  2x reduction; decode casts back to the leaf's native dtype.
- :class:`QuantCodec` — per-leaf int8 affine quantization (~4x on f32
  leaves). Lossy, so commits must run through :class:`ErrorFeedback`: the
  quantization error of every commit is kept worker-side and re-injected
  into the next delta instead of being lost (QSGD/DGC error feedback —
  the cumulative folded update tracks the true update stream). Center
  pulls have no accumulation to feed errors back into, so QuantCodec
  ships pulls as f16 casts rather than quantizing absolute weights.

Direction matters: ``kind="commit"`` encodes deltas (worker -> server),
``kind="pull"`` encodes the center (server -> worker). Both ends pass the
same ``kind`` for a given message, so no per-blob tag is needed.

Integer/bool leaves pass through raw under every codec — quantizing a
step counter would corrupt it silently.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from distkeras_tpu import telemetry
from distkeras_tpu.comms.chunking import leaf_buffer

Spec = Tuple[tuple, np.dtype]  # (shape, dtype) agreed out of band


def _is_float(dtype) -> bool:
    # np.floating covers f16/f32/f64; ml_dtypes extensions (bf16, fp8)
    # register as void-kind with a float name, so match by name too
    dt = np.dtype(dtype)
    return np.issubdtype(dt, np.floating) or dt.name.startswith(
        ("bfloat", "float8", "float4", "float6"))


def _from_bytes(blob, dtype, shape) -> np.ndarray:
    arr = np.frombuffer(blob, dtype=dtype)
    if arr.size != int(np.prod(shape)):
        raise ValueError(
            f"blob of {arr.size} elements does not match leaf shape {shape}")
    return arr.reshape(shape)


class Codec:
    """Leaf codec protocol. Stateless: one instance serves every
    connection/thread (the stateful half — error feedback — lives in
    :class:`ErrorFeedback`)."""

    name = "abstract"
    #: True when decode(encode(x)) != x in general; lossy commit paths must
    #: run through ErrorFeedback.
    lossy = False

    def encode(self, arr: np.ndarray, kind: str = "commit"):
        """Array -> bytes-like wire blob (zero-copy where exactness
        allows)."""
        raise NotImplementedError

    def decode(self, blob, shape, dtype, kind: str = "commit") -> np.ndarray:
        """Wire blob -> array of exactly (shape, dtype)."""
        raise NotImplementedError


class RawCodec(Codec):
    """Native bytes on the wire — exact, and zero-copy on encode."""

    name = "raw"

    def encode(self, arr, kind: str = "commit"):
        return leaf_buffer(arr)

    def decode(self, blob, shape, dtype, kind: str = "commit"):
        return _from_bytes(blob, dtype, shape)


class _CastCodec(Codec):
    """Float leaves cross the wire in a narrower float dtype."""

    lossy = True
    wire_dtype: np.dtype

    def encode(self, arr, kind: str = "commit"):
        if not _is_float(arr.dtype):
            return leaf_buffer(arr)
        return leaf_buffer(np.asarray(arr, dtype=self.wire_dtype))

    def decode(self, blob, shape, dtype, kind: str = "commit"):
        if not _is_float(dtype):
            return _from_bytes(blob, dtype, shape)
        wire = _from_bytes(blob, self.wire_dtype, shape)
        return np.asarray(wire, dtype=dtype)


class Fp16Codec(_CastCodec):
    name = "f16"
    wire_dtype = np.dtype(np.float16)


class Bf16Codec(_CastCodec):
    name = "bf16"

    @property
    def wire_dtype(self):
        import ml_dtypes  # registered by jax; local import keeps this
                          # module importable without it until bf16 is used
        return np.dtype(ml_dtypes.bfloat16)


def affine_qparams(lo: float, hi: float, levels: int):
    """Quantization step for an affine grid of ``levels + 1`` codes spanning
    ``[lo, hi]``. THE one scale rule shared by the wire codec (lo=min,
    hi=max, levels=255) and the in-step quantizer (lo=-amax, hi=+amax,
    levels=254 — symmetric int8; see distkeras_tpu/precision.py), so wire
    and step numerics cannot silently diverge."""
    return (hi - lo) / levels


def affine_quantize(a, lo, scale, levels, xp=np):
    """Codes in ``[0, levels]`` for the affine grid ``lo + scale * q``.
    Branchless (``xp`` may be jax.numpy inside a trace): a zero scale —
    constant leaf — maps every element to code 0, which dequantizes exactly.
    Division (not multiply-by-reciprocal) keeps codes bit-identical to the
    original wire arithmetic."""
    ok = scale > 0
    safe = xp.where(ok, scale, xp.ones_like(scale * 1.0))
    q = xp.clip(xp.rint((a - lo) / safe), 0, levels)
    return xp.where(ok, q, xp.zeros_like(q))


def affine_dequantize(q, lo, scale):
    """Inverse of affine_quantize: ``lo + scale * q`` (backend-agnostic)."""
    return lo + scale * q


class QuantCodec(Codec):
    """Per-leaf int8 affine quantization for commits; f16 casts for pulls.

    Commit blob layout: ``[f32 scale][f32 lo][uint8 payload]`` — decode is
    ``lo + scale * q``. Scale spans the leaf's own [min, max], so the
    per-element error is bounded by ``(max - min) / 255`` (asserted in
    tests/test_comms.py). A constant leaf encodes with scale 0 and decodes
    exactly.
    """

    name = "int8"
    lossy = True
    _LEVELS = 255
    _pull = Fp16Codec()

    def encode(self, arr, kind: str = "commit"):
        if not _is_float(arr.dtype):
            return leaf_buffer(arr)
        if kind == "pull":
            return self._pull.encode(arr, kind)
        a = np.asarray(arr, dtype=np.float32).reshape(-1)
        if a.size == 0:
            return b""
        lo, hi = float(a.min()), float(a.max())
        scale = float(affine_qparams(lo, hi, self._LEVELS))
        q = affine_quantize(a, np.float32(lo), np.float32(scale),
                            self._LEVELS, xp=np)
        head = np.array([scale, lo], dtype="<f4").tobytes()
        return head + q.astype(np.uint8).tobytes()

    def decode(self, blob, shape, dtype, kind: str = "commit"):
        if not _is_float(dtype):
            return _from_bytes(blob, dtype, shape)
        if kind == "pull":
            return self._pull.decode(blob, shape, dtype, kind)
        n = int(np.prod(shape))
        if n == 0:
            return np.zeros(shape, dtype)
        if len(blob) != 8 + n:
            raise ValueError(
                f"int8 blob of {len(blob)} bytes does not match leaf "
                f"shape {shape} (want {8 + n})")
        scale, lo = np.frombuffer(blob[:8], dtype="<f4")
        q = np.frombuffer(blob, dtype=np.uint8, offset=8)
        return affine_dequantize(
            q.astype(np.float32), np.float32(lo),
            np.float32(scale)).reshape(shape).astype(dtype)


_REGISTRY: Dict[str, Codec] = {
    c.name: c for c in (RawCodec(), Fp16Codec(), Bf16Codec(), QuantCodec())
}


def available_codecs() -> tuple:
    return tuple(sorted(_REGISTRY))


def get_codec(codec) -> Codec:
    """Resolve a codec by name (or pass a Codec instance through)."""
    if isinstance(codec, Codec):
        return codec
    try:
        return _REGISTRY[str(codec)]
    except KeyError:
        raise ValueError(f"Unknown codec {codec!r}; "
                         f"available: {available_codecs()}") from None


def negotiate(requested: str, supported: Iterable[str]) -> str:
    """Handshake rule shared by both ends: the server grants the requested
    codec when it supports it, otherwise both sides fall back to raw (raw
    is always legal — it is the seed wire format)."""
    return requested if requested in set(supported) | {"raw"} else "raw"


class ErrorFeedback:
    """Worker-side residual accumulation for lossy commit codecs.

    ``encode_leaves`` adjusts each float delta by the residual left over
    from previous encodes, encodes the adjusted value, and banks the new
    quantization error: over a run, the sum of what the server decoded
    equals the sum of the true deltas to within one step's quantization
    error — the error-feedback invariant (tests/test_comms.py asserts it).

    Thread-safe: host_async worker threads share one client and therefore
    one residual stream; the lock serializes adjust+bank so no delta's
    error is dropped or double-injected.
    """

    def __init__(self, codec: Codec):
        self.codec = get_codec(codec)
        self._residual: Optional[List[Optional[np.ndarray]]] = None
        self._lock = threading.Lock()

    def encode_leaves(self, leaves: Sequence[np.ndarray],
                      specs: Sequence[Spec]) -> list:
        if not self.codec.lossy:
            return [self.codec.encode(l, kind="commit") for l in leaves]
        with self._lock:
            if self._residual is None:
                self._residual = [
                    np.zeros(s, np.float32) if _is_float(d) else None
                    for s, d in specs]
            blobs = []
            for i, (leaf, (shape, dtype)) in enumerate(zip(leaves, specs)):
                res = self._residual[i]
                if res is None:  # integer leaf: exact under every codec
                    blobs.append(self.codec.encode(leaf, kind="commit"))
                    continue
                adj = np.asarray(leaf, np.float32) + res
                blob = self.codec.encode(adj, kind="commit")
                decoded = np.asarray(
                    self.codec.decode(bytes(blob), shape, dtype,
                                      kind="commit"), np.float32)
                self._residual[i] = adj - decoded
                blobs.append(blob)
            return blobs

    def reset(self) -> None:
        with self._lock:
            self._residual = None


class EncodedParameterServer:
    """Wrap a local ParameterServer so every pull/commit crosses the codec
    exactly as it would on the wire — no socket required.

    Two users: single-process ``codec=`` runs (the trainer sees the same
    numerics it would get against a remote service, so convergence tests
    don't need a loopback socket), and process 0 of a cross-process run
    (its workers hit the PS object directly; wrapping keeps their commits
    subject to the same lossy transform as every remote process's).
    """

    def __init__(self, ps, codec):
        self.ps = ps
        self.codec = get_codec(codec)
        self._ef = ErrorFeedback(self.codec)
        self._specs: Optional[List[Spec]] = None
        self._treedef = None

    def _flatten(self, tree):
        # trainer-host-only path: the codec MODULE stays jax-free (CPU
        # probes import it); flattening live pytrees necessarily needs jax
        import jax  # dktlint: disable=layer-forbidden-import

        from distkeras_tpu.utils.fetch import device_get_batched

        leaves, treedef = jax.tree_util.tree_flatten(
            device_get_batched(tree))
        leaves = [np.asarray(l) for l in leaves]
        if self._specs is None:
            self._specs = [(l.shape, l.dtype) for l in leaves]
            self._treedef = treedef
        return leaves

    def _roundtrip(self, tree, kind: str):
        # trainer-host-only path, same contract as _flatten above
        import jax  # dktlint: disable=layer-forbidden-import

        leaves = self._flatten(tree)
        if kind == "commit":
            blobs = self._ef.encode_leaves(leaves, self._specs)
        else:
            blobs = [self.codec.encode(l, kind=kind) for l in leaves]
        raw = sum(l.nbytes for l in leaves)
        wire = sum(len(b) for b in blobs)
        if wire:
            telemetry.histogram("comms.compress_ratio",
                                op=kind, path="local").record(raw / wire)
        out = [self.codec.decode(bytes(b), s, d, kind=kind)
               for b, (s, d) in zip(blobs, self._specs)]
        return jax.tree_util.tree_unflatten(self._treedef, out)

    # -- ParameterServer interface ---------------------------------------
    def pull(self):
        center, clock = self.ps.pull()
        if self.codec.name == "raw":
            return center, clock
        return self._roundtrip(center, "pull"), clock

    def commit(self, delta, last_update: int = 0) -> int:
        if self.codec.name == "raw":
            return self.ps.commit(delta, last_update=last_update)
        return self.ps.commit(self._roundtrip(delta, "commit"),
                              last_update=last_update)

    def initialize(self, params) -> None:
        self.ps.initialize(params)

    @property
    def num_updates(self) -> int:
        return self.ps.num_updates

    @num_updates.setter
    def num_updates(self, value: int) -> None:
        self.ps.num_updates = value

    def start(self) -> None:
        self.ps.start()

    def stop(self) -> None:
        self.ps.stop()
