"""Bounded reconnect/retry policy for the PS transport.

The fault-tolerant wire (DESIGN.md §13) retries a failed round-trip on a
fresh connection a bounded number of times, sleeping an exponentially
growing, jittered delay between attempts. The jitter is seeded (one RNG
per policy instance) so a scripted chaos test sees the same delay
sequence every run — retry behavior must be assertable, not timing luck.

Like the rest of ``comms/``, this module never imports jax.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule for transport retries.

    ``delay(attempt)`` for attempt 1, 2, ... is
    ``min(base_s * 2**(attempt-1), max_s)`` scaled by a uniform jitter in
    ``[1 - jitter, 1 + jitter]`` — full exponential backoff with
    decorrelation so N workers retrying a dead shard do not reconnect in
    lockstep.
    """

    max_retries: int = 4
    base_s: float = 0.05
    max_s: float = 2.0
    jitter: float = 0.25
    seed: int = 0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}")
        if self.base_s <= 0 or self.max_s < self.base_s:
            raise ValueError(
                f"need 0 < base_s <= max_s, got {self.base_s}/{self.max_s}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        # dataclass(frozen=True): route mutable state around the freeze
        object.__setattr__(self, "_rng", random.Random(self.seed))
        object.__setattr__(self, "_lock", threading.Lock())

    def delay(self, attempt: int) -> float:
        """Sleep time before retry ``attempt`` (1-based)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        raw = min(self.base_s * (2.0 ** (attempt - 1)), self.max_s)
        with self._lock:  # Random() is not thread-safe across workers
            scale = 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return raw * scale


#: Policy used when a caller passes none: a few quick retries, bounded
#: well under the history-barrier timeout so exhaustion surfaces as a
#: typed PSUnavailable instead of a silent stall.
DEFAULT_RETRY = RetryPolicy()
