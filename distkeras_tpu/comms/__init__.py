"""Wire codecs + chunked transport for the parameter-server path.

The async PS algorithms (DOWNPOUR/ADAG/DynSGD/EASGD) are bounded by the
commit/pull wire: full-precision leaf bytes per round-trip. This package
makes the wire pluggable — cast-on-wire (f16/bf16) and int8 affine
quantization with worker-side error feedback (QSGD, Alistarh et al. 2017;
DGC, Lin et al. 2018) — and provides chunked zero-copy buffer encoding so
large leaves never pay a full-tree copy on the way out.
"""

from distkeras_tpu.comms.chunking import (
    DEFAULT_CHUNK_BYTES,
    iter_chunks,
    leaf_buffer,
    send_buffers,
)
from distkeras_tpu.comms.codec import (
    Bf16Codec,
    Codec,
    EncodedParameterServer,
    ErrorFeedback,
    Fp16Codec,
    QuantCodec,
    RawCodec,
    available_codecs,
    get_codec,
    negotiate,
)
from distkeras_tpu.comms.retry import DEFAULT_RETRY, RetryPolicy

__all__ = [
    "Codec", "RawCodec", "Fp16Codec", "Bf16Codec", "QuantCodec",
    "ErrorFeedback", "EncodedParameterServer",
    "get_codec", "available_codecs", "negotiate",
    "leaf_buffer", "iter_chunks", "send_buffers", "DEFAULT_CHUNK_BYTES",
    "RetryPolicy", "DEFAULT_RETRY",
]
