"""Chunked zero-copy buffer encoding for large pytree leaves.

The original wire path (and ``utils/serialization.py``'s npz encoding)
materialized every leaf through ``tobytes()``/``BytesIO`` — a full copy of
the tree per send, paid again by the ``b"".join`` that framed the message.
The helpers here expose leaves as ``memoryview``s over their existing
storage and hand them to the socket (or a file) in bounded chunks, so the
only copies left are the kernel's.

Leaves with exotic dtypes (bf16 via ml_dtypes) are viewed as raw bytes —
the buffer protocol's format string never enters the picture, so any
fixed-itemsize dtype works.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

#: Per-sendall chunk bound: large enough to amortize syscalls, small enough
#: that no single kernel copy pins a multi-GB buffer.
DEFAULT_CHUNK_BYTES = 4 << 20


def leaf_buffer(arr) -> memoryview:
    """A zero-copy byte view of an array's storage (copy only if the input
    was non-contiguous). Works for any fixed-itemsize dtype, bf16 included."""
    a = np.ascontiguousarray(arr)
    flat = a.reshape(-1)  # view: `a` is contiguous
    if flat.dtype != np.uint8:
        flat = flat.view(np.uint8)
    return memoryview(flat)


def iter_chunks(buf, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> Iterator[memoryview]:
    """Slice a buffer into bounded memoryview windows (no copies)."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    for lo in range(0, len(mv), chunk_bytes):
        yield mv[lo:lo + chunk_bytes]


def send_buffers(sock, buffers: Sequence, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> int:
    """sendall a sequence of byte buffers in bounded chunks; returns the
    total bytes written. The caller frames the message (lengths travel in
    its header) — this is purely the copy-free egress."""
    total = 0
    for buf in buffers:
        for chunk in iter_chunks(buf, chunk_bytes):
            sock.sendall(chunk)
            total += len(chunk)
    return total


def write_buffers(fileobj, buffers: Sequence,
                  chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> int:
    """File counterpart of :func:`send_buffers` (checkpoint/serialization
    egress): stream buffers to ``fileobj.write`` without joining them."""
    total = 0
    for buf in buffers:
        for chunk in iter_chunks(buf, chunk_bytes):
            fileobj.write(chunk)
            total += len(chunk)
    return total
